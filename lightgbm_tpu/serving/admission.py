"""Admission control: deadline budgets, load shedding, brownout.

No reference equivalent — the reference predictor is a library call;
a standing replica under overload needs to refuse work it cannot
finish in time, and refuse it CHEAPLY (before any device dispatch).

The controller sits in front of the MicroBatcher and answers one
question per predict request: given the queue backlog and the EWMA
batch service time, will this request's deadline budget survive the
wait? Three outcomes, in order of escalation:

- admit: estimated wait fits the budget; the request queues normally.
- brownout: pressure is building (estimated wait above half the shed
  threshold) — the request is still served, but the quality monitors
  (drift/skew sampling, shadow scoring) are switched off to shed
  their overhead first. `/healthz` and `/metricz` are never touched:
  they bypass admission entirely (GET path).
- shed: estimated wait exceeds `shed_queue_budget` x budget — refuse
  with 429 + Retry-After sized to the backlog, before the request
  costs anything. A request whose deadline ALREADY passed gets 504
  (server.py checks expiry before calling assess()).

Deadline budgets come from the `X-Deadline-Ms` request header
(remaining milliseconds, the cross-service propagation idiom), falling
back to `deadline_default_ms`; with neither, the request has no
deadline and is never shed — admission is strictly opt-in, so the
PR-11 serving paths behave exactly as before unless a budget exists.

Wait estimation: queued requests coalesce (the whole point of the
batcher), so the backlog is counted in BATCHES — queue depth divided
by the observed requests-per-batch — times the EWMA service time, plus
one batch of slack for an in-flight dispatch and the coalescing wait
itself. Deliberately a cheap upper bound, not a simulation: shedding
a hair early under real overload beats queue collapse.

Brownout has hysteresis (engage at 0.5x the shed threshold, release
at 0.25x) so a flapping queue does not toggle the monitors per
request. State lands on /metricz (`brownout_active`, `shed_count`,
`deadline_expired_count`) — see docs/Resilience.md.
"""

import math
import threading
import time

# brownout engages when estimated wait crosses this fraction of the
# shed threshold, and releases below half of it (hysteresis)
BROWNOUT_ENGAGE = 0.5
BROWNOUT_RELEASE = 0.25

# floor for Retry-After so a shed client never busy-loops us
MIN_RETRY_AFTER_S = 0.05


class AdmissionController:
    """Per-server admission state. Thread-safe: handler threads call
    `assess` concurrently; brownout transitions happen under a lock."""

    def __init__(self, batcher, metrics=None, deadline_default_ms=0.0,
                 shed_queue_budget=1.0):
        self.batcher = batcher
        self.metrics = metrics
        self.deadline_default_ms = float(deadline_default_ms)
        self.shed_queue_budget = float(shed_queue_budget)
        self._lock = threading.Lock()
        self._brownout = False

    # ------------------------------------------------------------ deadlines
    def deadline_from_header(self, header_value, now=None):
        """Parse an `X-Deadline-Ms` header (remaining milliseconds)
        into an ABSOLUTE time.monotonic() deadline; unparsable or
        missing values fall back to `deadline_default_ms`. Returns
        None when the request carries no deadline at all."""
        now = time.monotonic() if now is None else now
        ms = None
        if header_value is not None:
            try:
                ms = float(header_value)
            except (TypeError, ValueError):
                ms = None
        if ms is None and self.deadline_default_ms > 0:
            ms = self.deadline_default_ms
        if ms is None:
            return None
        return now + ms / 1e3

    # ------------------------------------------------------------- estimate
    def estimated_wait_s(self):
        """Upper-bound estimate of how long a request admitted NOW
        waits before its batch completes: the coalescing wait plus
        (backlog batches + one in-flight batch) x EWMA service time."""
        est = self.batcher.estimated_service_s()
        if est <= 0.0:
            # cold start: no dispatch observed yet — assume one
            # coalescing window per batch so we never shed before the
            # first request has even been scored
            est = self.batcher.max_wait_s
        depth = self.batcher.queue_depth()
        per_batch = 1.0
        m = self.metrics
        if m is not None:
            batches = m.batch_count
            if batches:
                per_batch = max(1.0, m.batched_requests / batches)
        backlog_batches = math.ceil(depth / per_batch) if depth else 0
        return self.batcher.max_wait_s + (backlog_batches + 1) * est

    # --------------------------------------------------------------- verdict
    @property
    def brownout_active(self):
        return self._brownout

    def assess(self, deadline, now=None):
        """Admission verdict for one predict request. Returns
        ('admit', None) or ('shed', retry_after_s). Updates brownout
        state as a side effect (every request is a pressure sample).
        `deadline` is absolute monotonic or None (deadline-less
        requests are never shed but still sample pressure)."""
        now = time.monotonic() if now is None else now
        wait = self.estimated_wait_s()
        if deadline is None:
            self._update_brownout(0.0)
            return "admit", None
        budget = max(0.0, deadline - now)
        threshold = self.shed_queue_budget * budget
        pressure = wait / threshold if threshold > 0 else float("inf")
        self._update_brownout(pressure)
        if pressure <= 1.0:
            return "admit", None
        # Retry-After: when the CURRENT backlog should have drained
        retry_after = max(MIN_RETRY_AFTER_S, wait - budget)
        if self.metrics is not None:
            self.metrics.record_shed()
        return "shed", retry_after

    def trace_tags(self):
        """The controller's state as span tags (telemetry/disttrace.py):
        WHY a request was shed or browned out, readable straight off
        the /tracez per-hop breakdown."""
        return {"estimated_wait_ms": round(self.estimated_wait_s() * 1e3,
                                           3),
                "queue_depth": int(self.batcher.queue_depth()),
                "brownout": bool(self._brownout)}

    def _update_brownout(self, pressure):
        with self._lock:
            if not self._brownout and pressure >= BROWNOUT_ENGAGE:
                self._brownout = True
            elif self._brownout and pressure < BROWNOUT_RELEASE:
                self._brownout = False
            else:
                return
        if self.metrics is not None:
            self.metrics.set_brownout(self._brownout)
