"""Online inference subsystem: frozen compiled predictor, micro-batching
queue, HTTP serving endpoint (docs/Serving.md).

Import cost note: this package pulls in jax (via compiled_model); the
top-level `lightgbm_tpu` package does NOT import it so batch-training
users never pay for the serving stack.
"""

from .batcher import MicroBatcher
from .compiled_model import CompiledPredictor
from .metrics import ServingMetrics
from .server import build_monitors, drain, make_server, swap_model

__all__ = ["CompiledPredictor", "MicroBatcher", "ServingMetrics",
           "build_monitors", "drain", "make_server", "swap_model"]
