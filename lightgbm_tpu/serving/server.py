"""Serving endpoint: stdlib HTTP server around a CompiledPredictor.

`python -m lightgbm_tpu.serve model.txt --port 8099` loads the text
model, freezes + AOT-warms it (serving/compiled_model.py), starts the
micro-batching queue (serving/batcher.py) and serves:

- POST /predict          transformed predictions (sigmoid/softmax)
- POST /predict_raw      raw scores
- POST /predict_leaf     leaf indices
- GET  /healthz          liveness + model card
- GET  /metricz          request/row/batch counters, batch occupancy,
                         queue depth, p50/p95/p99 latency, warmup +
                         compile-cache stats, drift/skew gauges
- GET  /driftz           the drift & skew monitors' full view: rolling
                         per-feature PSI vs the training profile,
                         prediction-distribution histogram, shadow-
                         scoring skew counters (serving/drift.py;
                         requires a <model>.profile.json baseline)

Request body: JSON `{"rows": [[...], ...]}` (or `{"row": [...]}` for a
single row), or `text/csv` — one comma/tab-separated row per line.
Response: JSON `{"predictions": [[...], ...], "rows": N,
"latency_ms": ...}`.

ThreadingHTTPServer + MicroBatcher is the whole concurrency story:
each connection's handler thread blocks on its request's Future while
the single batcher worker coalesces everything that arrived within
`max_wait_ms` into one padded device dispatch. stdlib-only by design —
the serving layer must not add dependencies the training image lacks.
"""

import argparse
import json
import re
import signal
import sys
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

import numpy as np

from ..io.parser import NA_VALUES
from ..telemetry import prometheus
from ..utils.log import Log
from .batcher import MicroBatcher
from .compiled_model import DEFAULT_MAX_BATCH_ROWS, CompiledPredictor
from .metrics import ServingMetrics

DEFAULT_SLOW_REQUEST_MS = 1000.0

_REQUEST_ID_OK = re.compile(r"[^\w.\-]")


def _parse_rows(body, content_type):
    """Request body -> (N, F) float32 rows. JSON `rows`/`row` keys, or
    CSV/TSV lines (NaN/empty cells allowed — they ride the model's
    missing-value routing)."""
    if "csv" in (content_type or ""):
        lines = [ln for ln in body.decode("utf-8").splitlines()
                 if ln.strip()]
        sep = "\t" if lines and "\t" in lines[0] else ","
        na = set(NA_VALUES) | {""}  # the project-wide missing markers
        rows = [[float(tok) if tok.strip() not in na else float("nan")
                 for tok in ln.split(sep)]
                for ln in lines]
        return np.asarray(rows, dtype=np.float32)
    payload = json.loads(body)
    if isinstance(payload, dict):
        rows = payload.get("rows", payload.get("row"))
        if rows is None:
            raise ValueError('JSON body needs a "rows" (or "row") key')
    else:
        rows = payload  # bare list-of-lists
    if rows and not isinstance(rows[0], (list, tuple)):
        rows = [rows]
    # JSON null = missing value -> NaN (rides the model's NaN routing)
    arr = [[float("nan") if v is None else float(v) for v in r]
           for r in rows]
    return np.asarray(arr, dtype=np.float32).reshape(len(arr), -1)


class ServingHandler(BaseHTTPRequestHandler):
    """One request per handler-thread; heavy lifting rides the shared
    batcher."""

    protocol_version = "HTTP/1.1"
    # set by make_server():
    batcher = None
    metrics = None
    predictor = None
    slow_request_ms = DEFAULT_SLOW_REQUEST_MS
    drift = None     # serving/drift.py DriftMonitor (or None)
    skew = None      # serving/drift.py SkewMonitor (or None)

    def log_message(self, fmt, *args):
        # the structured access-log record (one per request, with id +
        # latency split) replaces the default per-line noise; keep the
        # raw lines reachable at debug for protocol-level forensics
        Log.debug("http: " + fmt, *args)

    def _request_id(self):
        """Caller's X-Request-Id (sanitized, bounded) or a fresh one —
        either way the response echoes it, so a slow request is
        greppable across client logs, access log and headers."""
        rid = _REQUEST_ID_OK.sub("", self.headers.get("X-Request-Id")
                                 or "")[:64]
        return rid or uuid.uuid4().hex[:16]

    def _reply(self, code, obj, headers=None):
        data = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(data)

    def _access_log(self, request_id, rows, status, timing_ms):
        """One structured record per request (request id, path, rows,
        status, latency split) — a JSON object under
        LIGHTGBM_TPU_LOG_JSON, key=value text otherwise."""
        Log.structured("Info", "access", request_id=request_id,
                       path=self.path.split("?")[0], rows=int(rows),
                       status=int(status), **(timing_ms or {}))

    def _metricz_snapshot(self):
        snap = self.metrics.snapshot()
        snap["queue_depth"] = self.batcher.queue_depth()
        stats = self.predictor.stats
        snap["warmup_s"] = stats["warmup_s"]
        snap["compile_cache_hits"] = stats["compile_cache_hits"]
        # True when AOT warmup was served by the persistent compile
        # cache (warm-process startup; config.py)
        snap["compile_cache_hit"] = stats["compile_cache_hits"] > 0
        snap["warm_dispatches"] = stats["warm_dispatches"]
        snap["cold_dispatches"] = stats["cold_dispatches"]
        snap["buckets"] = stats["buckets"]
        # drift/skew scalar gauges ride the same page (full view on
        # /driftz); absent monitors contribute nothing
        if self.drift is not None:
            snap.update(self.drift.gauges())
        if self.skew is not None:
            snap.update(self.skew.gauges())
        return snap

    def _prometheus(self):
        """The serving registry + the derived scalars (occupancy,
        queue depth, warmup stats) in text exposition format — the
        same page shape the training-side /metricz serves."""
        reg = self.metrics.registry.snapshot()
        owned = (set(reg.get("counters") or ())
                 | set(reg.get("gauges") or ())
                 | set(reg.get("histograms") or ()))
        extra = {k: v for k, v in self._metricz_snapshot().items()
                 if k not in owned
                 and isinstance(v, (int, float))
                 and not isinstance(v, bool)}
        if self.drift is not None:
            # one gauge per profiled feature: the scrape-side alerting
            # surface (`lightgbm_tpu_drift_psi_<feature>`)
            for name, value in self.drift.psi_by_feature().items():
                extra[f"drift_psi_{name}"] = value
        return prometheus.render(reg, extra_gauges=extra)

    def do_GET(self):
        parts = urlsplit(self.path)
        fmt = (parse_qs(parts.query).get("format") or [""])[0]
        if parts.path.startswith("/healthz"):
            self._reply(200, {"status": "ok",
                              "model": self.predictor.describe()})
        elif parts.path.startswith("/driftz"):
            out = {"enabled": self.drift is not None
                   or self.skew is not None}
            if self.drift is not None:
                out.update(self.drift.snapshot())
            out["skew"] = (self.skew.snapshot()
                           if self.skew is not None else None)
            self._reply(200, out)
        elif parts.path.startswith("/metricz"):
            if fmt == "prometheus":
                data = self._prometheus().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", prometheus.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            else:
                self._reply(200, self._metricz_snapshot())
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        req_id = self._request_id()
        id_hdr = {"X-Request-Id": req_id}
        # drain the body BEFORE any reply: on an HTTP/1.1 keep-alive
        # connection unread body bytes would be parsed as the next
        # request line, poisoning the client's next call
        if "chunked" in (self.headers.get("Transfer-Encoding")
                         or "").lower():
            self.close_connection = True  # un-drainable without a length
            self._reply(411, {"error": "chunked bodies not supported; "
                                       "send Content-Length",
                              "request_id": req_id}, id_hdr)
            self._access_log(req_id, 0, 411, None)
            return
        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
        except ValueError:
            self.close_connection = True  # length unknown: can't drain
            self._reply(400, {"error": "malformed Content-Length",
                              "request_id": req_id}, id_hdr)
            self._access_log(req_id, 0, 400, None)
            return
        body = self.rfile.read(length) if length > 0 else b""
        # the clock starts AFTER the body drain: latency_ms and the
        # parse/queue/compute split measure server-side work only — a
        # slow client upload must not pollute the /metricz percentiles
        # or fire slow_request alerts
        t0 = time.monotonic()
        kind = {"/predict": "predict", "/predict_raw": "raw",
                "/predict_leaf": "leaf"}.get(self.path.split("?")[0])
        if kind is None:
            self._reply(404, {"error": f"unknown path {self.path}",
                              "request_id": req_id}, id_hdr)
            self._access_log(req_id, 0, 404, None)
            return
        try:
            rows = _parse_rows(body, self.headers.get("Content-Type"))
            if rows.size == 0:
                raise ValueError("no rows in request body")
        except Exception as e:  # malformed request: the CALLER's fault
            self.metrics.record_error()
            self._reply(400, {"error": str(e), "request_id": req_id},
                        id_hdr)
            self._access_log(req_id, 0, 400, None)
            return
        t_parsed = time.monotonic()
        fut = None
        try:
            fut = self.batcher.submit(rows, kind=kind)
            out = fut.result(timeout=60.0)
        except Exception as e:  # dispatch fault/timeout: OUR fault — a
            self.metrics.record_error()  # 4xx would read as a caller
            self._reply(500, {"error": str(e),  # error and stop retries
                              "request_id": req_id}, id_hdr)
            self._access_log(req_id, rows.shape[0], 500, None)
            return
        latency = time.monotonic() - t0
        # the per-request latency split (docs/Serving.md): parse is this
        # handler thread, queue is enqueue->batch dispatch (time spent
        # waiting for company), compute is the coalesced device call the
        # request rode (batcher future timestamps)
        timing = {"parse_ms": round((t_parsed - t0) * 1e3, 3),
                  "total_ms": round(latency * 1e3, 3)}
        if fut.t_dispatch is not None and fut.t_done is not None:
            timing["queue_ms"] = round(
                (fut.t_dispatch - fut.t_enqueue) * 1e3, 3)
            timing["compute_ms"] = round(
                (fut.t_done - fut.t_dispatch) * 1e3, 3)
        self.metrics.record_request(rows.shape[0], latency)
        headers = dict(id_hdr)
        headers["X-Timing-Ms"] = ";".join(
            f"{k[:-3]}={v}" for k, v in sorted(timing.items()))
        self._reply(200, {"predictions": np.asarray(out).tolist(),
                          "rows": int(rows.shape[0]),
                          "latency_ms": round(latency * 1e3, 3),
                          "request_id": req_id,
                          "timing_ms": timing}, headers)
        slow = self.slow_request_ms
        if slow and latency * 1e3 >= slow:
            Log.structured("Warning", "slow_request", request_id=req_id,
                           path=self.path.split("?")[0],
                           rows=int(rows.shape[0]),
                           threshold_ms=slow, **timing)
        self._access_log(req_id, rows.shape[0], 200, timing)
        # drift/skew intake AFTER the reply: sampled monitoring must
        # never add to the latency the client (or /metricz) sees
        self._observe_quality(kind, rows, out)

    def _observe_quality(self, kind, rows, out):
        """Feed the drift monitor (sampled row histograms + the
        prediction distribution) and the skew monitor (sampled host
        f64 shadow scoring). Never raises — a monitor defect must not
        poison the keep-alive connection."""
        if self.drift is None and self.skew is None:
            return
        try:
            if self.drift is not None:
                # the monitor reduces multiclass outputs to the
                # winning-class confidence at flush — pass the batcher
                # output through untouched (request path stays cheap)
                self.drift.observe(
                    rows, predictions=out if kind == "predict" else None)
            if self.skew is not None and kind in ("predict", "raw"):
                self.skew.observe(rows, out, kind)
        except Exception as e:
            Log.warning("drift/skew monitor failed: %s", e)


def make_server(predictor, host="127.0.0.1", port=8099, max_wait_ms=2.0,
                max_batch_rows=None,
                slow_request_ms=DEFAULT_SLOW_REQUEST_MS,
                drift=None, skew=None):
    """Wire predictor + batcher + metrics (+ optional drift/skew
    monitors, serving/drift.py) into a ThreadingHTTPServer (not yet
    serving — call serve_forever, or use it from tests)."""
    metrics = ServingMetrics()
    batcher = MicroBatcher(predictor,
                           max_batch_rows=max_batch_rows,
                           max_wait_ms=max_wait_ms, metrics=metrics)
    handler = type("BoundServingHandler", (ServingHandler,),
                   {"batcher": batcher, "metrics": metrics,
                    "predictor": predictor,
                    "slow_request_ms": float(slow_request_ms or 0.0),
                    "drift": drift, "skew": skew})
    srv = ThreadingHTTPServer((host, port), handler)
    srv.batcher = batcher
    srv.metrics = metrics
    srv.predictor = predictor
    srv.drift = drift
    srv.skew = skew
    return srv


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.serve",
        description="Serve a trained model over HTTP with micro-batching "
                    "(docs/Serving.md)")
    ap.add_argument("model", help="model file (text format)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8099)
    ap.add_argument("--max-batch-rows", type=int,
                    default=DEFAULT_MAX_BATCH_ROWS,
                    help="largest coalesced dispatch; also the largest "
                         "pre-compiled row bucket")
    ap.add_argument("--max-wait-ms", type=float, default=2.0,
                    help="how long a lone request waits for company")
    ap.add_argument("--slow-request-ms", type=float,
                    default=DEFAULT_SLOW_REQUEST_MS,
                    help="requests slower than this emit a structured "
                         "slow-request log line (0 = off; mirrors the "
                         "slow_request_ms config knob)")
    ap.add_argument("--num-iteration", type=int, default=-1,
                    help="serve only the first N iterations of the model")
    from .drift import (DEFAULT_DRIFT_SAMPLE_RATE, DEFAULT_PSI_WARN,
                        DEFAULT_SKEW_SAMPLE_RATE, DEFAULT_SKEW_WARN)
    from ..io.profile import DEFAULT_PROFILE_BINS, model_profile_path
    ap.add_argument("--profile", default="",
                    help="training dataset profile JSON (default: "
                         "<model>.profile.json when it exists); the "
                         "drift monitor's baseline distribution")
    ap.add_argument("--drift-sample-rate", type=float,
                    default=DEFAULT_DRIFT_SAMPLE_RATE,
                    help="fraction of request rows fed to the drift "
                         "monitor (0 = off; mirrors the "
                         "drift_sample_rate config knob)")
    ap.add_argument("--psi-warn", type=float, default=DEFAULT_PSI_WARN,
                    help="per-feature PSI threshold for the structured "
                         "drift_warn log (mirrors psi_warn)")
    ap.add_argument("--profile-bins", type=int,
                    default=DEFAULT_PROFILE_BINS,
                    help="max histogram groups per feature for PSI "
                         "(mirrors profile_bins)")
    ap.add_argument("--skew-sample-rate", type=float,
                    default=DEFAULT_SKEW_SAMPLE_RATE,
                    help="fraction of request rows shadow-scored "
                         "through the host f64 reference path (0 = "
                         "off; mirrors skew_sample_rate)")
    ap.add_argument("--skew-warn", type=int, default=DEFAULT_SKEW_WARN,
                    help="diverging-row count that triggers the "
                         "structured skew_warn log (mirrors skew_warn)")
    args = ap.parse_args(argv)

    t0 = time.time()
    predictor = CompiledPredictor.from_model_file(
        args.model, num_iteration=args.num_iteration,
        max_batch_rows=args.max_batch_rows)
    drift = skew = None
    if args.drift_sample_rate > 0:
        import os
        from ..io.profile import DatasetProfile
        from .drift import DriftMonitor
        profile_path = args.profile or model_profile_path(args.model)
        if os.path.exists(profile_path):
            profile = DatasetProfile.load(profile_path)
            # transformed binary/multiclass predictions live in [0, 1]
            pred_range = ((0.0, 1.0)
                          if predictor.sigmoid > 0
                          or predictor.num_class > 1 else None)
            drift = DriftMonitor(profile,
                                 sample_rate=args.drift_sample_rate,
                                 psi_warn=args.psi_warn,
                                 profile_bins=args.profile_bins,
                                 pred_range=pred_range)
            Log.info("drift monitor on: %d profiled features, sample "
                     "rate %.3f, psi_warn %.2f (%s)",
                     profile.num_features, args.drift_sample_rate,
                     args.psi_warn, profile_path)
        else:
            Log.warning("drift monitor off: no training profile at %s "
                        "(train with a build that writes "
                        "<model>.profile.json, or pass --profile)",
                        profile_path)
    if args.skew_sample_rate > 0:
        from .drift import SkewMonitor, host_reference_scorer
        skew = SkewMonitor(host_reference_scorer(args.model),
                           sample_rate=args.skew_sample_rate,
                           skew_warn=args.skew_warn)
        Log.info("skew monitor on: sample rate %.3f, warn at %d "
                 "diverging row(s)", args.skew_sample_rate,
                 args.skew_warn)
    srv = make_server(predictor, host=args.host, port=args.port,
                      max_wait_ms=args.max_wait_ms,
                      max_batch_rows=args.max_batch_rows,
                      slow_request_ms=args.slow_request_ms,
                      drift=drift, skew=skew)
    Log.info("serving %s on http://%s:%d (%d trees, load+warm %.2fs, "
             "%d compile-cache hits)", args.model, args.host, args.port,
             predictor.num_trees, time.time() - t0,
             predictor.stats["compile_cache_hits"])
    # the driver-facing readiness line: tests and orchestrators wait
    # for this exact prefix on stdout before sending traffic
    print(f"SERVING http://{args.host}:{srv.server_address[1]}",
          flush=True)

    def shut(signum, frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, shut)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
        srv.batcher.close()
        Log.info("serving stopped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
