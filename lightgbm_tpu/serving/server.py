"""Serving endpoint: stdlib HTTP server around a CompiledPredictor.

`python -m lightgbm_tpu.serve model.txt --port 8099` loads the text
model, freezes + AOT-warms it (serving/compiled_model.py), starts the
micro-batching queue (serving/batcher.py) and serves:

- POST /predict          transformed predictions (sigmoid/softmax)
- POST /predict_raw      raw scores
- POST /predict_leaf     leaf indices
- GET  /healthz          liveness + model card (+ served model version)
- GET  /metricz          request/row/batch counters, batch occupancy,
                         queue depth, p50/p95/p99 latency, warmup +
                         compile-cache stats, drift/skew gauges,
                         model version + hot-swap counters
- GET  /driftz           the drift & skew monitors' full view: rolling
                         per-feature PSI vs the training profile,
                         prediction-distribution histogram, shadow-
                         scoring skew counters (serving/drift.py;
                         requires a <model>.profile.json baseline)
- GET  /quiescez         admin drain check: 200 when no request is in
                         flight and the batcher is idle, 503 otherwise
                         (clean hot-flips and rolling restarts wait on
                         this)

Hot-swap: `swap_model` flips the served model atomically under the
batcher (one predictor snapshot per coalesced batch — a response is
never scored by two model versions), and `--registry DIR --follow`
polls a fleet ModelRegistry so promotions/rollbacks land in a running
server without restart (lightgbm_tpu/fleet/, docs/Fleet.md). SIGTERM
drains: connections keep being ACCEPTED but new POSTs bounce with a
retryable 503 while in-flight requests finish (bounded by
--drain-timeout-s); only then does the listener close and the process
exit.

Resilience (docs/Resilience.md): an `X-Deadline-Ms` header carries the
client's remaining budget — requests that expire in the queue are
dropped BEFORE dispatch (504), and the admission controller
(serving/admission.py) sheds with 429 + Retry-After when the estimated
queue wait exceeds the budget, browning out the drift/skew monitors
first. `/healthz?strict=1` goes non-200 while draining so the fleet
router (fleet/router.py) ejects this replica before the listener
closes. Chaos faults (utils/faults.py: slow_replica_ms, error_rate,
drop_connection, wedge_batcher) are injectable per-server for the
resilience suite.

Request body: JSON `{"rows": [[...], ...]}` (or `{"row": [...]}` for a
single row), or `text/csv` — one comma/tab-separated row per line.
Response: JSON `{"predictions": [[...], ...], "rows": N,
"latency_ms": ...}`.

ThreadingHTTPServer + MicroBatcher is the whole concurrency story:
each connection's handler thread blocks on its request's Future while
the single batcher worker coalesces everything that arrived within
`max_wait_ms` into one padded device dispatch. stdlib-only by design —
the serving layer must not add dependencies the training image lacks.
"""

import argparse
import json
import re
import signal
import sys
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

import numpy as np

from ..io.parser import NA_VALUES
from ..telemetry import disttrace
from ..telemetry import prometheus
from ..utils import faults
from ..utils.log import Log
from .admission import AdmissionController
from .batcher import DeadlineExceeded, MicroBatcher
from .compiled_model import DEFAULT_MAX_BATCH_ROWS, CompiledPredictor
from .metrics import ServingMetrics

DEFAULT_SLOW_REQUEST_MS = 1000.0

_REQUEST_ID_OK = re.compile(r"[^\w.\-]")


def _parse_rows(body, content_type):
    """Request body -> (N, F) float32 rows. JSON `rows`/`row` keys, or
    CSV/TSV lines (NaN/empty cells allowed — they ride the model's
    missing-value routing)."""
    if "csv" in (content_type or ""):
        lines = [ln for ln in body.decode("utf-8").splitlines()
                 if ln.strip()]
        sep = "\t" if lines and "\t" in lines[0] else ","
        na = set(NA_VALUES) | {""}  # the project-wide missing markers
        rows = [[float(tok) if tok.strip() not in na else float("nan")
                 for tok in ln.split(sep)]
                for ln in lines]
        return np.asarray(rows, dtype=np.float32)
    payload = json.loads(body)
    if isinstance(payload, dict):
        rows = payload.get("rows", payload.get("row"))
        if rows is None:
            raise ValueError('JSON body needs a "rows" (or "row") key')
    else:
        rows = payload  # bare list-of-lists
    if rows and not isinstance(rows[0], (list, tuple)):
        rows = [rows]
    # JSON null = missing value -> NaN (rides the model's NaN routing)
    arr = [[float("nan") if v is None else float(v) for v in r]
           for r in rows]
    return np.asarray(arr, dtype=np.float32).reshape(len(arr), -1)


class ServingHandler(BaseHTTPRequestHandler):
    """One request per handler-thread; heavy lifting rides the shared
    batcher."""

    protocol_version = "HTTP/1.1"
    # set by make_server():
    batcher = None
    metrics = None
    slow_request_ms = DEFAULT_SLOW_REQUEST_MS
    # (owner_predictor, drift, skew) — THE monitor reference, swapped
    # as ONE tuple assignment: _observe_quality reads it atomically
    # and only feeds the monitors results their OWN model scored, so a
    # hot-swap mid-request cannot pair one model's output with
    # another's baseline/reference (a false, unretractable skew_warn
    # otherwise). The read-only endpoints (/driftz, /metricz) view the
    # same tuple through the drift/skew properties below.
    monitor_state = (None, None, None)

    @property
    def drift(self):
        return self.monitor_state[1]   # serving/drift.py DriftMonitor

    @property
    def skew(self):
        return self.monitor_state[2]   # serving/drift.py SkewMonitor

    @property
    def predictor(self):
        # the batcher's reference is THE served model — reading it here
        # keeps /healthz + /metricz consistent with what dispatches
        # score, including across a hot-swap (swap_model)
        return self.batcher.predictor

    def log_message(self, fmt, *args):
        # the structured access-log record (one per request, with id +
        # latency split) replaces the default per-line noise; keep the
        # raw lines reachable at debug for protocol-level forensics
        Log.debug("http: " + fmt, *args)

    def _request_id(self):
        """Caller's X-Request-Id (sanitized, bounded) or a fresh one —
        either way the response echoes it, so a slow request is
        greppable across client logs, access log and headers."""
        rid = _REQUEST_ID_OK.sub("", self.headers.get("X-Request-Id")
                                 or "")[:64]
        return rid or uuid.uuid4().hex[:16]

    def _reply(self, code, obj, headers=None):
        root = getattr(self, "_trace_root", None)
        if root is not None:
            # every reply path funnels here: the root span's outcome
            # tag (what tail sampling keys on) cannot be missed
            root.set_tag("http.status", int(code))
        data = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(data)

    def _access_log(self, request_id, rows, status, timing_ms):
        """One structured record per request (request id, path, rows,
        status, latency split) — a JSON object under
        LIGHTGBM_TPU_LOG_JSON, key=value text otherwise."""
        Log.structured("Info", "access", request_id=request_id,
                       path=self.path.split("?")[0], rows=int(rows),
                       status=int(status), **(timing_ms or {}))

    def _metricz_snapshot(self):
        snap = self.metrics.snapshot()
        snap["queue_depth"] = self.batcher.queue_depth()
        predictor = self.predictor
        stats = predictor.stats
        snap["warmup_s"] = stats["warmup_s"]
        snap["compile_cache_hits"] = stats["compile_cache_hits"]
        # True when AOT warmup was served by the persistent compile
        # cache (warm-process startup; config.py)
        snap["compile_cache_hit"] = stats["compile_cache_hits"] > 0
        snap["warm_dispatches"] = stats["warm_dispatches"]
        snap["cold_dispatches"] = stats["cold_dispatches"]
        snap["buckets"] = stats["buckets"]
        # fleet surface: which model generation is serving, how it got
        # here (docs/Fleet.md)
        srv = self.server
        snap["model_version"] = getattr(srv, "model_version", None)
        snap["swap_count"] = int(getattr(srv, "swap_count", 0))
        snap["serving_precision"] = getattr(predictor,
                                            "serving_precision", "f32")
        snap["accuracy_bound"] = float(getattr(predictor,
                                               "accuracy_bound", 0.0))
        snap["in_flight"] = int(getattr(srv, "inflight").count
                                if hasattr(srv, "inflight") else 0)
        snap["draining"] = bool(getattr(srv, "draining", False))
        # drift/skew scalar gauges ride the same page (full view on
        # /driftz); absent monitors contribute nothing
        if self.drift is not None:
            snap.update(self.drift.gauges())
        if self.skew is not None:
            snap.update(self.skew.gauges())
        return snap

    def _prometheus(self):
        """The serving registry + the derived scalars (occupancy,
        queue depth, warmup stats) in text exposition format — the
        same page shape the training-side /metricz serves."""
        reg = self.metrics.registry.snapshot()
        owned = (set(reg.get("counters") or ())
                 | set(reg.get("gauges") or ())
                 | set(reg.get("histograms") or ()))
        extra = {k: v for k, v in self._metricz_snapshot().items()
                 if k not in owned
                 and isinstance(v, (int, float))
                 and not isinstance(v, bool)}
        if self.drift is not None:
            # one gauge per profiled feature: the scrape-side alerting
            # surface (`lightgbm_tpu_drift_psi_<feature>`)
            for name, value in self.drift.psi_by_feature().items():
                extra[f"drift_psi_{name}"] = value
        return prometheus.render(reg, extra_gauges=extra)

    def do_GET(self):
        parts = urlsplit(self.path)
        fmt = (parse_qs(parts.query).get("format") or [""])[0]
        if parts.path.startswith("/healthz"):
            # the router ejects on `?strict=1`: a DRAINING replica is
            # alive (plain probes stay 200 for process supervisors)
            # but must stop receiving new traffic before its listener
            # closes — strict probes go non-200 the moment the drain
            # flag is set (docs/Resilience.md)
            draining = bool(getattr(self.server, "draining", False))
            strict = (parse_qs(parts.query).get("strict") or ["0"])[0]
            code = 503 if draining and strict not in ("", "0") else 200
            self._reply(code, {"status": "draining" if draining
                                         else "ok",
                               "draining": draining,
                               "model": self.predictor.describe(),
                               "model_version": getattr(
                                   self.server, "model_version", None)})
        elif parts.path.startswith("/quiescez"):
            # admin drain check: a clean flip/restart waits for 200
            srv = self.server
            in_flight = (srv.inflight.count
                         if hasattr(srv, "inflight") else 0)
            queued = self.batcher.queue_depth()
            idle = self.batcher.quiescent()
            quiescent = in_flight == 0 and queued == 0 and idle
            self._reply(200 if quiescent else 503, {
                "quiescent": quiescent,
                "draining": bool(getattr(srv, "draining", False)),
                "in_flight": int(in_flight),
                "queue_depth": int(queued),
                "batcher_idle": bool(idle)})
        elif parts.path.startswith("/driftz"):
            out = {"enabled": self.drift is not None
                   or self.skew is not None}
            if self.drift is not None:
                out.update(self.drift.snapshot())
            out["skew"] = (self.skew.snapshot()
                           if self.skew is not None else None)
            self._reply(200, out)
        elif parts.path.startswith("/metricz"):
            if fmt == "prometheus":
                data = self._prometheus().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", prometheus.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            else:
                self._reply(200, self._metricz_snapshot())
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        srv = self.server
        gauge = getattr(srv, "inflight", None)
        # the gauge increments BEFORE the draining check: the reverse
        # order would let drain() observe a false quiescent between a
        # handler passing the check and registering itself, tearing
        # the batcher down under a live request
        if gauge is not None:
            gauge.inc()
        try:
            if getattr(srv, "draining", False):
                # shutting down: refuse new work with a retryable
                # status so the drain converges (in-flight requests
                # still finish). Bounced requests stay auditable:
                # they count as errors and land in the access log
                req_id = self._request_id()
                self.close_connection = True
                self.metrics.record_error()
                self._reply(503, {"error": "draining: server is "
                                           "shutting down",
                                  "request_id": req_id},
                            {"X-Request-Id": req_id,
                             # a sibling replica can take this NOW —
                             # the hint just stops tight retry loops
                             "Retry-After": "1"})
                self._access_log(req_id, 0, 503, None)
                return
            self._handle_post()
        finally:
            if gauge is not None:
                gauge.dec()

    def _handle_post(self):
        """Trace shell around the predict path: opens the replica-side
        root span (continuing the router's X-Trace-Ctx when present),
        keeps it active for the handler thread so the batcher future
        inherits it, and closes it with the reply's http.status. An
        unhandled exception dumps the flight recorder first — the
        blackbox is most valuable exactly when the handler dies."""
        rec = getattr(self.server, "trace_recorder", None)
        if rec is None or not rec.enabled:
            self._serve_predict()
            return
        ctx = disttrace.parse_header(
            self.headers.get(disttrace.TRACE_HEADER) or "")
        root = rec.start("serve.request", ctx=ctx, kind="server",
                         tags={"component": "serving",
                               "path": self.path.split("?")[0]})
        self._trace_root = root
        t0 = time.monotonic()
        try:
            with disttrace.activate(root.context()):
                self._serve_predict()
        except Exception:
            disttrace.FLIGHT.dump("unhandled_server_exception",
                                  path=self.path.split("?")[0])
            rec.finish(root, status="error",
                       elapsed=time.monotonic() - t0)
            self._trace_root = None
            raise
        code = root.tags.get("http.status")
        rec.finish(root,
                   status="error" if isinstance(code, int)
                   and code >= 500 else "ok",
                   elapsed=time.monotonic() - t0)
        self._trace_root = None

    def _trace_observe(self, name, start, duration_s, **tags):
        """Synthesize a child span of this request's root from stamps
        taken elsewhere (parse split, queue wait). No-op untraced."""
        root = getattr(self, "_trace_root", None)
        rec = getattr(self.server, "trace_recorder", None)
        if root is None or rec is None:
            return
        rec.observe(name, root.context(), start, max(0.0, duration_s),
                    tags=tags or None)

    def _serve_predict(self):
        req_id = self._request_id()
        id_hdr = {"X-Request-Id": req_id}
        # drain the body BEFORE any reply: on an HTTP/1.1 keep-alive
        # connection unread body bytes would be parsed as the next
        # request line, poisoning the client's next call
        if "chunked" in (self.headers.get("Transfer-Encoding")
                         or "").lower():
            self.close_connection = True  # un-drainable without a length
            self._reply(411, {"error": "chunked bodies not supported; "
                                       "send Content-Length",
                              "request_id": req_id}, id_hdr)
            self._access_log(req_id, 0, 411, None)
            return
        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
        except ValueError:
            self.close_connection = True  # length unknown: can't drain
            self._reply(400, {"error": "malformed Content-Length",
                              "request_id": req_id}, id_hdr)
            self._access_log(req_id, 0, 400, None)
            return
        body = self.rfile.read(length) if length > 0 else b""
        # the clock starts AFTER the body drain: latency_ms and the
        # parse/queue/compute split measure server-side work only — a
        # slow client upload must not pollute the /metricz percentiles
        # or fire slow_request alerts
        t0 = time.monotonic()
        w0 = time.time()   # wall anchor for synthesized trace spans
        kind = {"/predict": "predict", "/predict_raw": "raw",
                "/predict_leaf": "leaf"}.get(self.path.split("?")[0])
        if kind is None:
            self._reply(404, {"error": f"unknown path {self.path}",
                              "request_id": req_id}, id_hdr)
            self._access_log(req_id, 0, 404, None)
            return
        try:
            rows = _parse_rows(body, self.headers.get("Content-Type"))
            if rows.size == 0:
                raise ValueError("no rows in request body")
        except Exception as e:  # malformed request: the CALLER's fault
            self.metrics.record_error()
            self._reply(400, {"error": str(e), "request_id": req_id},
                        id_hdr)
            self._access_log(req_id, 0, 400, None)
            return
        t_parsed = time.monotonic()
        self._trace_observe("serve.parse", w0, t_parsed - t0,
                            rows=int(rows.shape[0]))
        srv = self.server
        # ---- chaos hooks (utils/faults serving faults; no-ops unless
        # a fault is armed globally or on this server's overrides dict)
        chaos = faults.serving_chaos(getattr(srv, "chaos", None))
        if chaos:
            slow = chaos.get("slow_replica_ms")
            if slow:
                time.sleep(float(slow) / 1e3)
            if faults.consume_from("drop_connection",
                                   getattr(srv, "chaos", None)):
                # torn connection: no response bytes at all — the
                # router must see a transport error, not a status
                self.close_connection = True
                self._access_log(req_id, rows.shape[0], 0, None)
                return
            if faults.error_rate_fires(
                    getattr(srv, "chaos_error_state", {}),
                    chaos.get("error_rate")):
                self.metrics.record_error()
                self._reply(500, {"error": "injected fault: error_rate",
                                  "request_id": req_id}, id_hdr)
                self._access_log(req_id, rows.shape[0], 500, None)
                return
        # ---- deadline + admission (serving/admission.py): refuse work
        # we cannot finish in time BEFORE it costs a device dispatch
        admission = getattr(srv, "admission", None)
        deadline = None
        if admission is not None:
            t_adm0 = time.monotonic()
            deadline = admission.deadline_from_header(
                self.headers.get("X-Deadline-Ms"), now=t_parsed)
            if deadline is not None and deadline <= time.monotonic():
                self.metrics.record_deadline_expired()
                root = getattr(self, "_trace_root", None)
                if root is not None:
                    root.set_tag("decision", "deadline_expired")
                self._reply(504, {"error": "deadline already expired",
                                  "request_id": req_id}, id_hdr)
                self._access_log(req_id, rows.shape[0], 504, None)
                return
            verdict, retry_after = admission.assess(deadline)
            self._trace_observe(
                "serve.admission", w0 + (t_adm0 - t0),
                time.monotonic() - t_adm0, decision=verdict,
                **admission.trace_tags())
            if verdict == "shed":
                root = getattr(self, "_trace_root", None)
                if root is not None:
                    root.set_tag("decision", "shed")
                headers = dict(id_hdr)
                headers["Retry-After"] = str(
                    max(1, int(round(retry_after))))
                self._reply(429, {"error": "shedding load: queue wait "
                                           "exceeds deadline budget",
                                  "retry_after_s": round(retry_after, 3),
                                  "request_id": req_id}, headers)
                self._access_log(req_id, rows.shape[0], 429, None)
                return
        fut = None
        try:
            fut = self.batcher.submit(rows, kind=kind, deadline=deadline)
            out = fut.result(timeout=60.0)
        except DeadlineExceeded:
            # expired while queued: the batcher dropped it before any
            # device time was spent (504 — the client already moved on)
            self.metrics.record_deadline_expired()
            root = getattr(self, "_trace_root", None)
            if root is not None:
                root.set_tag("decision", "expired_in_queue")
            self._reply(504, {"error": "deadline expired in queue",
                              "request_id": req_id}, id_hdr)
            self._access_log(req_id, rows.shape[0], 504, None)
            return
        except Exception as e:  # dispatch fault/timeout: OUR fault — a
            self.metrics.record_error()  # 4xx would read as a caller
            self._reply(500, {"error": str(e),  # error and stop retries
                              "request_id": req_id}, id_hdr)
            self._access_log(req_id, rows.shape[0], 500, None)
            return
        latency = time.monotonic() - t0
        # the per-request latency split (docs/Serving.md): parse is this
        # handler thread, queue is enqueue->batch dispatch (time spent
        # waiting for company), compute is the coalesced device call the
        # request rode (batcher future timestamps)
        timing = {"parse_ms": round((t_parsed - t0) * 1e3, 3),
                  "total_ms": round(latency * 1e3, 3)}
        if fut.t_dispatch is not None and fut.t_done is not None:
            timing["queue_ms"] = round(
                (fut.t_dispatch - fut.t_enqueue) * 1e3, 3)
            timing["compute_ms"] = round(
                (fut.t_done - fut.t_dispatch) * 1e3, 3)
            # queue = enqueue -> batch dispatch; the dispatch + kernel
            # spans themselves come from the batcher worker (with links
            # to every coalesced member)
            self._trace_observe("serve.queue",
                                w0 + (fut.t_enqueue - t0),
                                fut.t_dispatch - fut.t_enqueue)
        self.metrics.record_request(rows.shape[0], latency)
        headers = dict(id_hdr)
        headers["X-Timing-Ms"] = ";".join(
            f"{k[:-3]}={v}" for k, v in sorted(timing.items()))
        self._reply(200, {"predictions": np.asarray(out).tolist(),
                          "rows": int(rows.shape[0]),
                          "latency_ms": round(latency * 1e3, 3),
                          "request_id": req_id,
                          "timing_ms": timing}, headers)
        slow = self.slow_request_ms
        if slow and latency * 1e3 >= slow:
            Log.structured("Warning", "slow_request", request_id=req_id,
                           path=self.path.split("?")[0],
                           rows=int(rows.shape[0]),
                           threshold_ms=slow, **timing)
        self._access_log(req_id, rows.shape[0], 200, timing)
        # drift/skew intake AFTER the reply: sampled monitoring must
        # never add to the latency the client (or /metricz) sees
        self._observe_quality(kind, rows, out, fut)

    def _observe_quality(self, kind, rows, out, fut=None):
        """Feed the drift monitor (sampled row histograms + the
        prediction distribution) and the skew monitor (sampled host
        f64 shadow scoring). Never raises — a monitor defect must not
        poison the keep-alive connection. A request whose batch was
        scored by a DIFFERENT predictor than the monitors' owner (a
        hot-swap landed mid-request) is skipped: sampled monitoring
        can drop one sample, a false skew alarm cannot be retracted."""
        owner, drift, skew = self.monitor_state   # ONE atomic read
        if drift is None and skew is None:
            return
        admission = getattr(self.server, "admission", None)
        if admission is not None and admission.brownout_active:
            # brownout: quality monitoring is the FIRST thing shed
            # under pressure — monitors drop samples gracefully, predict
            # traffic does not (docs/Resilience.md)
            return
        scored_by = getattr(fut, "scored_by", None)
        if scored_by is not None and scored_by is not owner:
            return
        try:
            if drift is not None:
                # the monitor reduces multiclass outputs to the
                # winning-class confidence at flush — pass the batcher
                # output through untouched (request path stays cheap)
                drift.observe(
                    rows, predictions=out if kind == "predict" else None)
            if skew is not None and kind in ("predict", "raw"):
                skew.observe(rows, out, kind)
        except Exception as e:
            Log.warning("drift/skew monitor failed: %s", e)


class _InflightGauge:
    """Count of POST requests currently inside a handler thread (the
    /quiescez drain check's second leg — the batcher queue only sees a
    request between submit and future-resolve)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def inc(self):
        with self._lock:
            self._count += 1

    def dec(self):
        with self._lock:
            self._count -= 1

    @property
    def count(self):
        with self._lock:
            return self._count


def build_monitors(predictor, drift_sample_rate=0.0, psi_warn=None,
                   profile_bins=None, skew_sample_rate=0.0,
                   skew_warn=None, profile_path=None):
    """Construct the (drift, skew) monitor pair for one predictor from
    the serve CLI's monitor knobs. The drift baseline comes from the
    predictor's auto-discovered profile sidecar
    (CompiledPredictor.from_model_file) unless `profile_path`
    overrides; the skew reference loads from `predictor.model_path`,
    and its tolerance is widened to the predictor's pinned
    `accuracy_bound` so a reduced-precision model keeps shadow scoring
    armed AND quiet (compiled_model.py). Either monitor is None when
    its inputs are off/absent. Hot-swaps rebuild both against the new
    model (fleet/hotswap.py)."""
    from ..io.profile import DEFAULT_PROFILE_BINS, DatasetProfile
    from .drift import (DEFAULT_PSI_WARN, DEFAULT_SKEW_WARN, SKEW_TOL,
                        DriftMonitor, SkewMonitor, host_reference_scorer)
    drift = skew = None
    if drift_sample_rate and drift_sample_rate > 0:
        profile = predictor.profile
        if profile_path:
            try:
                profile = DatasetProfile.load(profile_path)
            except (OSError, ValueError) as e:
                # a stale --profile path degrades to drift-off with a
                # warning (the pre-fleet behavior), never a boot crash
                Log.warning("cannot load profile %s (%s); falling back "
                            "to the model's own sidecar", profile_path, e)
                profile = predictor.profile
        if profile is not None:
            pred_range = ((0.0, 1.0)
                          if predictor.sigmoid > 0
                          or predictor.num_class > 1 else None)
            drift = DriftMonitor(
                profile, sample_rate=drift_sample_rate,
                psi_warn=(DEFAULT_PSI_WARN if psi_warn is None
                          else psi_warn),
                profile_bins=(DEFAULT_PROFILE_BINS if profile_bins is None
                              else profile_bins),
                pred_range=pred_range)
        else:
            Log.warning("drift monitor off: predictor has no profile "
                        "baseline (train with a build that writes "
                        "<model>.profile.json, or pass --profile)")
    if skew_sample_rate and skew_sample_rate > 0:
        if predictor.model_path:
            skew = SkewMonitor(
                host_reference_scorer(predictor.model_path),
                sample_rate=skew_sample_rate,
                skew_warn=(DEFAULT_SKEW_WARN if skew_warn is None
                           else skew_warn),
                tol=max(SKEW_TOL,
                        float(getattr(predictor, "accuracy_bound", 0.0))))
        else:
            Log.warning("skew monitor off: predictor has no model file "
                        "to load the host reference from")
    return drift, skew


def swap_model(srv, predictor, drift=None, skew=None, version=None):
    """Atomically flip a live server to a new (already warmed)
    predictor. Order matters: the batcher flips FIRST (dispatch
    provenance — one model per coalesced batch, fleet/hotswap.py),
    then the monitor/metadata surfaces follow; the monitor_owner tag
    keeps in-flight requests scored by the OTHER model out of the new
    monitors (ServingHandler._observe_quality). Returns the retired
    predictor."""
    old = srv.batcher.swap_predictor(predictor)
    handler = srv.RequestHandlerClass
    handler.monitor_state = (predictor, drift, skew)
    srv.model_version = version
    srv.swap_count = int(getattr(srv, "swap_count", 0)) + 1
    Log.info("hot-swap: now serving version=%s trees=%d leaves=%s "
             "precision=%s", version, predictor.num_trees,
             "linear" if getattr(predictor, "is_linear", False)
             else "constant", predictor.serving_precision)
    return old


class ServingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer whose `predictor` delegates to the batcher —
    the batcher's reference is THE served model (one source of truth),
    so a caller flipping via `batcher.swap_predictor` directly can
    never desync the server-level view."""

    @property
    def predictor(self):
        return self.batcher.predictor


def make_server(predictor, host="127.0.0.1", port=8099, max_wait_ms=2.0,
                max_batch_rows=None,
                slow_request_ms=DEFAULT_SLOW_REQUEST_MS,
                drift=None, skew=None, model_version=None,
                monitor_settings=None, deadline_default_ms=0.0,
                shed_queue_budget=1.0, trace_dir=None, trace_rank=0,
                trace_sample_rate=disttrace.DEFAULT_SAMPLE_RATE,
                trace_slow_only=False):
    """Wire predictor + batcher + metrics (+ optional drift/skew
    monitors, serving/drift.py) into a ThreadingHTTPServer (not yet
    serving — call serve_forever, or use it from tests).
    `monitor_settings` (the build_monitors kwargs) are remembered on
    the server so a hot-swap can rebuild monitors for the new model.
    `trace_dir` arms distributed tracing (telemetry/disttrace.py):
    request spans journal there, tail-sampled, for the aggregator's
    collector; the flight recorder registers this server's evidence."""
    metrics = ServingMetrics()
    batcher = MicroBatcher(predictor,
                           max_batch_rows=max_batch_rows,
                           max_wait_ms=max_wait_ms, metrics=metrics)
    handler = type("BoundServingHandler", (ServingHandler,),
                   {"batcher": batcher, "metrics": metrics,
                    "slow_request_ms": float(slow_request_ms or 0.0),
                    "monitor_state": (predictor, drift, skew)})
    srv = ServingHTTPServer((host, port), handler)
    srv.batcher = batcher
    srv.metrics = metrics
    srv.trace_recorder = None
    if trace_dir:
        srv.trace_recorder = disttrace.TraceRecorder(
            directory=trace_dir, rank=trace_rank, service="serving",
            sample_rate=trace_sample_rate,
            slow_ms=float(slow_request_ms or 0.0),
            slow_only=trace_slow_only)
        batcher.trace_recorder = srv.trace_recorder
        # arm the blackbox beside the trace journal: on an unhandled
        # handler exception / SIGQUIT the last seconds land on disk
        disttrace.FLIGHT.configure(trace_dir, rank=trace_rank)
        disttrace.FLIGHT.add_source(
            "serving_metrics", lambda: metrics.snapshot())
        disttrace.FLIGHT.add_source(
            "trace_stats", srv.trace_recorder.stats)
    srv.model_version = model_version
    srv.swap_count = 0
    srv.inflight = _InflightGauge()
    srv.draining = False
    srv.monitor_settings = dict(monitor_settings or {})
    # resilience layer (serving/admission.py, docs/Resilience.md)
    srv.admission = AdmissionController(
        batcher, metrics=metrics,
        deadline_default_ms=deadline_default_ms,
        shed_queue_budget=shed_queue_budget)
    # per-server chaos overrides (utils/faults.serving_chaos): the
    # chaos harness slows/breaks ONE in-process replica through this
    # dict; the batcher shares it for `wedge_batcher`
    srv.chaos = {}
    srv.chaos_error_state = {}
    batcher.chaos = srv.chaos
    return srv


def drain(srv, timeout_s=30.0, poll_s=0.05):
    """Wait until no POST is in flight and the batcher is idle (or the
    timeout passes). Callers set `srv.draining = True` first so new
    work bounces with 503 and the wait converges. Returns True when
    fully quiesced."""
    deadline = time.monotonic() + float(timeout_s)
    while time.monotonic() < deadline:
        if srv.inflight.count == 0 and srv.batcher.quiescent():
            return True
        time.sleep(poll_s)
    return srv.inflight.count == 0 and srv.batcher.quiescent()


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.serve",
        description="Serve a trained model over HTTP with micro-batching "
                    "(docs/Serving.md)")
    ap.add_argument("model", nargs="?", default=None,
                    help="model file (text format); optional when "
                         "--registry points at a registry with a live "
                         "version")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8099)
    ap.add_argument("--registry", default="",
                    help="fleet model-registry directory (docs/Fleet.md):"
                         " serve its CURRENT version (the positional "
                         "model is a fallback while the registry is "
                         "empty)")
    ap.add_argument("--follow", action="store_true",
                    help="poll the registry and hot-swap to promotions/"
                         "rollbacks without restart (requires "
                         "--registry)")
    ap.add_argument("--poll-s", type=float, default=2.0,
                    help="registry poll interval for --follow")
    ap.add_argument("--serving-precision", default="f32",
                    choices=("f32", "bf16"),
                    help="f32 = exact serving contract; bf16 = reduced-"
                         "precision value stage with a pinned accuracy "
                         "bound the skew monitor adopts "
                         "(docs/Serving.md)")
    ap.add_argument("--drain-timeout-s", type=float, default=30.0,
                    help="SIGTERM drain: how long to wait for in-flight "
                         "requests before exiting")
    ap.add_argument("--max-batch-rows", type=int,
                    default=DEFAULT_MAX_BATCH_ROWS,
                    help="largest coalesced dispatch; also the largest "
                         "pre-compiled row bucket")
    ap.add_argument("--max-wait-ms", type=float, default=2.0,
                    help="how long a lone request waits for company")
    ap.add_argument("--slow-request-ms", type=float,
                    default=DEFAULT_SLOW_REQUEST_MS,
                    help="requests slower than this emit a structured "
                         "slow-request log line (0 = off; mirrors the "
                         "slow_request_ms config knob)")
    ap.add_argument("--deadline-default-ms", type=float, default=0.0,
                    help="deadline budget assumed for requests without "
                         "an X-Deadline-Ms header (0 = such requests "
                         "are never deadline-shed; mirrors the "
                         "deadline_default_ms config knob)")
    ap.add_argument("--shed-queue-budget", type=float, default=1.0,
                    help="shed (429) when estimated queue wait exceeds "
                         "this fraction of the deadline budget; "
                         "brownout engages at half of it (mirrors the "
                         "shed_queue_budget config knob)")
    ap.add_argument("--num-iteration", type=int, default=-1,
                    help="serve only the first N iterations of the model")
    ap.add_argument("--trace-dir", default="",
                    help="arm distributed tracing: journal tail-sampled "
                         "trace records here for the aggregator's "
                         "collector (telemetry/disttrace.py, "
                         "docs/Observability.md)")
    ap.add_argument("--trace-rank", type=int, default=0,
                    help="journal rank suffix for this replica's trace "
                         "records (keep distinct per process sharing "
                         "--trace-dir)")
    ap.add_argument("--trace-sample-rate", type=float, default=0.01,
                    help="deterministic hash(trace_id) fraction of "
                         "healthy traces to keep; error/slow traces "
                         "are always kept (mirrors the "
                         "trace_sample_rate config knob)")
    ap.add_argument("--trace-slow-only", action="store_true",
                    help="keep only error/slow traces, dropping even "
                         "hash-sampled healthy ones (mirrors "
                         "trace_slow_only)")
    ap.add_argument("--no-blackbox", action="store_true",
                    help="disable the crash flight recorder dump "
                         "(blackbox-<rank>.json; mirrors the blackbox "
                         "config knob)")
    from .drift import (DEFAULT_DRIFT_SAMPLE_RATE, DEFAULT_PSI_WARN,
                        DEFAULT_SKEW_SAMPLE_RATE, DEFAULT_SKEW_WARN)
    from ..io.profile import DEFAULT_PROFILE_BINS
    ap.add_argument("--profile", default="",
                    help="training dataset profile JSON (default: "
                         "<model>.profile.json when it exists); the "
                         "drift monitor's baseline distribution")
    ap.add_argument("--drift-sample-rate", type=float,
                    default=DEFAULT_DRIFT_SAMPLE_RATE,
                    help="fraction of request rows fed to the drift "
                         "monitor (0 = off; mirrors the "
                         "drift_sample_rate config knob)")
    ap.add_argument("--psi-warn", type=float, default=DEFAULT_PSI_WARN,
                    help="per-feature PSI threshold for the structured "
                         "drift_warn log (mirrors psi_warn)")
    ap.add_argument("--profile-bins", type=int,
                    default=DEFAULT_PROFILE_BINS,
                    help="max histogram groups per feature for PSI "
                         "(mirrors profile_bins)")
    ap.add_argument("--skew-sample-rate", type=float,
                    default=DEFAULT_SKEW_SAMPLE_RATE,
                    help="fraction of request rows shadow-scored "
                         "through the host f64 reference path (0 = "
                         "off; mirrors skew_sample_rate)")
    ap.add_argument("--skew-warn", type=int, default=DEFAULT_SKEW_WARN,
                    help="diverging-row count that triggers the "
                         "structured skew_warn log (mirrors skew_warn)")
    args = ap.parse_args(argv)
    if args.follow and not args.registry:
        ap.error("--follow requires --registry")

    t0 = time.time()
    registry = None
    model_path, model_version = args.model, None
    if args.registry:
        from ..fleet.registry import ModelRegistry
        registry = ModelRegistry(args.registry)
        cur = registry.current()
        if cur is not None:
            model_version = int(cur["version"])
            # same CRC discipline as every follower hot-swap: bit rot
            # in the live version must fail the boot, not get served
            registry.verify(model_version)
            model_path = registry.model_path(model_version)
            Log.info("serving registry %s CURRENT v%d (manifest "
                     "verified)", args.registry, model_version)
    if not model_path:
        ap.error("no model: pass a model file or --registry with a "
                 "promoted version")
    predictor = CompiledPredictor.from_model_file(
        model_path, num_iteration=args.num_iteration,
        max_batch_rows=args.max_batch_rows,
        serving_precision=args.serving_precision)
    monitor_settings = dict(
        drift_sample_rate=args.drift_sample_rate,
        psi_warn=args.psi_warn, profile_bins=args.profile_bins,
        skew_sample_rate=args.skew_sample_rate,
        skew_warn=args.skew_warn)
    drift, skew = build_monitors(predictor, profile_path=args.profile,
                                 **monitor_settings)
    if drift is not None:
        Log.info("drift monitor on: %d profiled features, sample rate "
                 "%.3f, psi_warn %.2f", drift.profile.num_features,
                 args.drift_sample_rate, args.psi_warn)
    if skew is not None:
        Log.info("skew monitor on: sample rate %.3f, warn at %d "
                 "diverging row(s), tol %.3g", args.skew_sample_rate,
                 args.skew_warn, skew.tol)
    srv = make_server(predictor, host=args.host, port=args.port,
                      max_wait_ms=args.max_wait_ms,
                      max_batch_rows=args.max_batch_rows,
                      slow_request_ms=args.slow_request_ms,
                      drift=drift, skew=skew,
                      model_version=model_version,
                      monitor_settings=monitor_settings,
                      deadline_default_ms=args.deadline_default_ms,
                      shed_queue_budget=args.shed_queue_budget,
                      trace_dir=args.trace_dir or None,
                      trace_rank=args.trace_rank,
                      trace_sample_rate=args.trace_sample_rate,
                      trace_slow_only=args.trace_slow_only)
    if args.no_blackbox:
        disttrace.FLIGHT.disarm()
    elif args.trace_dir:
        # SIGQUIT -> blackbox without killing the process: live
        # inspection of a replica that looks wedged
        disttrace.FLIGHT.install_sigquit()
    # the swap path re-applies this knob to every challenger
    # (fleet/hotswap.py HotSwapper)
    srv.num_iteration = args.num_iteration
    follower = None
    if args.follow:
        from ..fleet.hotswap import attach_follower
        follower = attach_follower(srv, registry, poll_s=args.poll_s,
                                   serving_precision=args.serving_precision)
        Log.info("following registry %s every %.1fs", args.registry,
                 args.poll_s)
    Log.info("serving %s on http://%s:%d (%d trees, %s, load+warm "
             "%.2fs, %d compile-cache hits)", model_path, args.host,
             args.port, predictor.num_trees, args.serving_precision,
             time.time() - t0, predictor.stats["compile_cache_hits"])
    # the driver-facing readiness line: tests and orchestrators wait
    # for this exact prefix on stdout before sending traffic
    print(f"SERVING http://{args.host}:{srv.server_address[1]}",
          flush=True)

    stop = threading.Event()

    def shut(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, shut)
    serve_thread = threading.Thread(target=srv.serve_forever,
                                    daemon=True)
    serve_thread.start()
    try:
        while not stop.wait(0.2):
            pass
    except KeyboardInterrupt:
        pass
    finally:
        # graceful drain: KEEP accepting while draining (so brand-new
        # connections get the retryable 503 from a handler instead of
        # hanging on an un-accepted socket), let in-flight requests
        # finish, THEN stop the listener and tear down
        srv.draining = True
        if follower is not None:
            follower.stop()
        drained = drain(srv, timeout_s=args.drain_timeout_s)
        srv.shutdown()
        serve_thread.join(timeout=10)
        srv.server_close()
        srv.batcher.close()
        if srv.trace_recorder is not None:
            srv.trace_recorder.close()
        Log.structured("Info", "drain", drained=bool(drained),
                       in_flight=srv.inflight.count,
                       queue_depth=srv.batcher.queue_depth())
        Log.info("serving stopped (%s)",
                 "drained" if drained else "drain timeout")
    return 0


if __name__ == "__main__":
    sys.exit(main())
