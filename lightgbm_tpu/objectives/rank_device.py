"""Device LambdaRank: padded-query pairwise gradients as one XLA program.

Reference: src/objective/rank_objective.hpp:19-227 (GetGradientsForOneQuery)
runs an OpenMP loop over queries, each building an O(n_q^2) pair sweep on
the CPU. TPU-first design: queries are padded to a rectangle (Q, M)
(M = largest query), document indices gather scores from the flat score
vector, and the full pairwise (Q, M, M) tensor is computed batched on
device — argsort ranks, NDCG deltas, sigmoid responses — then gradients
scatter back through the same index map. Queries are processed in blocks
under `lax.map` so peak memory is O(block * M^2) regardless of Q.

The reference's 1M-entry sigmoid lookup table is replaced by the exact
expression with the same clamping range (a CPU latency trick, not a
semantic feature). Pair math runs in float32 on device (the reference
uses double on CPU); tests pin the difference against the float64 host
path to ~1e-4 relative.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..metrics.dcg_calculator import DCGCalculator, K_MAX_POSITION

# max f32 elements in one (block, M, M) pair tensor (~64 MB)
_PAIR_BUDGET = 16 * 1024 * 1024


class PaddedQueryLayout:
    """Static padded-query indexing shared by the objective and metric."""

    def __init__(self, query_boundaries, num_data):
        qb = np.asarray(query_boundaries, dtype=np.int64)
        self.counts = np.diff(qb)
        self.num_queries = len(self.counts)
        self.num_data = int(num_data)
        self.max_docs = int(self.counts.max()) if len(self.counts) else 1
        # query block size under the pair-tensor budget
        qb_rows = max(1, _PAIR_BUDGET // (self.max_docs * self.max_docs))
        qb_rows = min(qb_rows, max(self.num_queries, 1))
        self.block_queries = qb_rows
        self.num_blocks = -(-self.num_queries // qb_rows)
        self.padded_queries = self.num_blocks * qb_rows
        # (Qp, M) row indices; padded slots point at the sink row N
        idx = np.full((self.padded_queries, self.max_docs), self.num_data,
                      dtype=np.int32)
        for q in range(self.num_queries):
            lo, hi = qb[q], qb[q + 1]
            idx[q, : hi - lo] = np.arange(lo, hi, dtype=np.int32)
        self.idx = idx
        self.mask = idx < self.num_data


def make_lambdarank_gradfn(layout: PaddedQueryLayout, label, label_gain,
                           sigmoid, max_position, weights):
    """Build the jitted (score (1, N)) -> (grad, hess) device function."""
    dcg = DCGCalculator(label_gain)
    lab = np.asarray(label, dtype=np.int64)
    Qp, M = layout.idx.shape
    lab_p = np.where(layout.mask, lab[np.minimum(layout.idx, layout.num_data - 1)], 0)
    lg_p = dcg.label_gain[lab_p] * layout.mask                     # (Qp, M)
    inv = np.zeros(Qp)
    for q in range(layout.num_queries):
        maxdcg = dcg.cal_maxdcg_at_k(
            max_position, lab[layout.idx[q][layout.mask[q]]])
        inv[q] = 1.0 / maxdcg if maxdcg > 0 else 0.0
    w_p = None
    if weights is not None:
        w = np.asarray(weights, dtype=np.float32)
        w_p = np.where(layout.mask,
                       w[np.minimum(layout.idx, layout.num_data - 1)], 0.0)

    nb, qb = layout.num_blocks, layout.block_queries
    idx_d = jnp.asarray(layout.idx.reshape(nb, qb, M))
    mask_d = jnp.asarray(layout.mask.reshape(nb, qb, M))
    lg_d = jnp.asarray(lg_p.reshape(nb, qb, M), dtype=jnp.float32)
    inv_d = jnp.asarray(inv.reshape(nb, qb), dtype=jnp.float32)
    w_d = (None if w_p is None
           else jnp.asarray(w_p.reshape(nb, qb, M), dtype=jnp.float32))
    disc_lut = jnp.asarray(dcg.discount, dtype=jnp.float32)
    sig = float(sigmoid)
    min_in = -50.0 / sig / 2.0
    max_in = -min_in
    n = layout.num_data

    def one_block(args):
        idx_b, mask_b, lg_b, inv_b, w_b, s_flat = args
        s = jnp.where(mask_b, jnp.take(s_flat, idx_b), -jnp.inf)   # (qb, M)
        order = jnp.argsort(-s, axis=1, stable=True)
        ranks = jnp.argsort(order, axis=1, stable=True)
        disc = jnp.take(disc_lut, jnp.minimum(ranks, K_MAX_POSITION - 1))
        cnt = jnp.sum(mask_b, axis=1).astype(jnp.int32)
        best = jnp.take_along_axis(s, order[:, :1], 1)[:, 0]
        wpos = jnp.maximum(cnt - 1, 0)[:, None]
        worst = jnp.take_along_axis(
            s, jnp.take_along_axis(order, wpos, 1), 1)[:, 0]
        # rank_objective.hpp: skip a kMinScore sentinel at the bottom
        worst2 = jnp.take_along_axis(
            s, jnp.take_along_axis(order, jnp.maximum(wpos - 1, 0), 1), 1)[:, 0]
        worst = jnp.where(jnp.isneginf(worst) & (cnt > 1), worst2, worst)
        norm = (best != worst)

        sm = jnp.where(mask_b, s, 0.0)
        ds = sm[:, :, None] - sm[:, None, :]                       # (qb, M, M)
        dcg_gap = lg_b[:, :, None] - lg_b[:, None, :]
        pmask = (dcg_gap > 0) & mask_b[:, :, None] & mask_b[:, None, :]
        pd = jnp.abs(disc[:, :, None] - disc[:, None, :])
        delta = dcg_gap * pd * inv_b[:, None, None]
        delta = jnp.where(norm[:, None, None],
                          delta / (0.01 + jnp.abs(ds)), delta)
        x = jnp.clip(ds, min_in, max_in)
        p = 2.0 / (1.0 + jnp.exp(2.0 * x * sig))
        ph = p * (2.0 - p)
        lam = jnp.where(pmask, -p * delta, 0.0)
        hes = jnp.where(pmask, 2.0 * ph * delta, 0.0)
        g = lam.sum(axis=2) - lam.sum(axis=1)
        h = hes.sum(axis=2) + hes.sum(axis=1)
        if w_b is not None:
            g = g * w_b
            h = h * w_b
        return g * mask_b, h * mask_b

    @jax.jit
    def grad_fn(score):
        s_flat = jnp.concatenate([score[0].astype(jnp.float32),
                                  jnp.zeros(1, jnp.float32)])
        if w_d is None:
            g_b, h_b = jax.lax.map(
                lambda a: one_block((*a, None, s_flat)),
                (idx_d, mask_d, lg_d, inv_d))
        else:
            g_b, h_b = jax.lax.map(
                lambda a: one_block((*a, s_flat)),
                (idx_d, mask_d, lg_d, inv_d, w_d))
        flat_idx = idx_d.reshape(-1)
        grad = jnp.zeros(n + 1, jnp.float32).at[flat_idx].add(g_b.reshape(-1))
        hess = jnp.zeros(n + 1, jnp.float32).at[flat_idx].add(h_b.reshape(-1))
        return grad[None, :n], hess[None, :n]

    return grad_fn


def ndcg_eval_padded(layout: PaddedQueryLayout, label, label_gain, eval_at,
                     score, query_weights=None):
    """Vectorized padded NDCG@k (rank_metric.hpp:16-165): one argsort over
    (Q, M) instead of a Python loop over queries."""
    dcg = DCGCalculator(label_gain)
    lab = np.asarray(label, dtype=np.int64)
    Q, M = layout.num_queries, layout.max_docs
    idx = layout.idx[:Q]
    mask = layout.mask[:Q]
    s = np.where(mask, np.asarray(score, dtype=np.float64)[
        np.minimum(idx, layout.num_data - 1)], -np.inf)
    lg = dcg.label_gain[np.where(mask, lab[np.minimum(idx, layout.num_data - 1)], 0)]
    order = np.argsort(-s, axis=1, kind="stable")
    gains_ranked = np.take_along_axis(lg * mask, order, axis=1)     # (Q, M)
    # positions beyond the discount LUT clamp to its last entry (the
    # gradient path applies the same clamp on ranks)
    disc_m = dcg.discount[np.minimum(np.arange(M), K_MAX_POSITION - 1)]
    cum = np.cumsum(gains_ranked * disc_m[None, :], axis=1)
    # ideal ordering for maxdcg
    ideal = np.sort(lg * mask, axis=1)[:, ::-1]
    cum_ideal = np.cumsum(ideal * disc_m[None, :], axis=1)
    cnt = mask.sum(axis=1)
    qw = (np.ones(Q) if query_weights is None
          else np.asarray(query_weights, dtype=np.float64))
    out = []
    for k in eval_at:
        kk = np.minimum(int(k), cnt) - 1                            # (Q,)
        kk_safe = np.maximum(kk, 0)
        dcg_k = np.take_along_axis(cum, kk_safe[:, None], 1)[:, 0]
        max_k = np.take_along_axis(cum_ideal, kk_safe[:, None], 1)[:, 0]
        ndcg = np.where(max_k > 0, dcg_k / np.maximum(max_k, 1e-300), 1.0)
        ndcg = np.where(cnt > 0, ndcg, 1.0)
        out.append(float(np.sum(qw * ndcg) / np.sum(qw)))
    return out
