"""Objective implementations. See package docstring for design notes."""

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.log import Log

K_MIN_SCORE = -np.inf


class ObjectiveFunction:
    """Interface (include/LightGBM/objective_function.h:31-32).

    Objectives with a jittable gradient also expose the PURE form
    `_grad_pure(ops, score)` with its device operands `_grad_ops` (a
    pytree of per-row arrays). The fused trainer (models/gbdt.py
    _get_fused_fn) feeds those operands as runtime ARGUMENTS instead of
    letting the jit close over them: closed-over arrays embed their
    VALUES in the lowered HLO, so any label perturbation would change
    the program bytes and defeat the persistent compile cache."""

    name = "none"
    _grad_pure = None   # staticmethod-like (ops, score) -> (g, h)
    _grad_ops = None    # pytree of device operands for _grad_pure

    def init(self, metadata, num_data):
        self.num_data = num_data
        self.label = np.asarray(metadata.label, dtype=np.float32)
        self.weights = (None if metadata.weights is None
                        else np.asarray(metadata.weights, dtype=np.float32))
        # guardrail: a NaN/Inf label or weight poisons every gradient of
        # every iteration — fail at init with the offending row instead
        # of training garbage trees (utils/guardrails.py)
        from ..utils.guardrails import validate_labels
        validate_labels(self.label, self.weights)

    def _install_grad(self, grad_pure, ops):
        """Register a pure gradient: adds the optional row weights to
        `ops`, stores the (_grad_pure, _grad_ops) pair for the fused
        trainer, and keeps the closed-over jitted `_grad` for the
        sequential path."""
        if self.weights is not None:
            ops["weights"] = jnp.asarray(self.weights)
        self._grad_ops = ops
        self._grad_pure = grad_pure
        self._grad = jax.jit(lambda score: grad_pure(ops, score))

    def get_gradients(self, score):
        """score: (K, N) device array -> (grad, hess) each (K, N)."""
        raise NotImplementedError


class RegressionL2loss(ObjectiveFunction):
    """L2 regression (regression_objective.hpp:10-52)."""

    name = "regression"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)

        def _grad_pure(ops, score):
            s = score[0]
            weights = ops.get("weights")
            if weights is not None:
                g = (s - ops["label"]) * weights
                h = jnp.broadcast_to(weights, s.shape)
            else:
                g = s - ops["label"]
                h = jnp.ones_like(s)
            return g[None, :], h[None, :]

        self._install_grad(_grad_pure, {"label": jnp.asarray(self.label)})

    def get_gradients(self, score):
        return self._grad(score)


class BinaryLogloss(ObjectiveFunction):
    """Binary logloss with sigmoid scaling / unbalance / scale_pos_weight
    (binary_objective.hpp:13-109)."""

    name = "binary"

    def __init__(self, config):
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0.0:
            Log.fatal("Sigmoid parameter %f should be greater than zero", self.sigmoid)
        self.is_unbalance = bool(config.is_unbalance)
        self.scale_pos_weight = float(config.scale_pos_weight)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        cnt_positive = int(np.sum(self.label == 1))
        cnt_negative = num_data - cnt_positive
        Log.info("Number of postive: %d, number of negative: %d",
                 cnt_positive, cnt_negative)
        if cnt_positive == 0 or cnt_negative == 0:
            Log.fatal("Training data only contains one class")
        label_weights = [1.0, 1.0]
        if self.is_unbalance:
            if cnt_positive > cnt_negative:
                label_weights[0] = cnt_positive / cnt_negative
            else:
                label_weights[1] = cnt_negative / cnt_positive
        label_weights[1] *= self.scale_pos_weight

        sig = self.sigmoid

        def _grad_pure(ops, score):
            s = score[0]
            sign, lw = ops["sign"], ops["lw"]
            response = -2.0 * sign * sig / (1.0 + jnp.exp(2.0 * sign * sig * s))
            abs_response = jnp.abs(response)
            g = response * lw
            h = abs_response * (2.0 * sig - abs_response) * lw
            weights = ops.get("weights")
            if weights is not None:
                g = g * weights
                h = h * weights
            return g[None, :], h[None, :]

        self._install_grad(_grad_pure, {
            "sign": jnp.asarray(np.where(self.label == 1, 1.0, -1.0),
                                dtype=jnp.float32),
            "lw": jnp.asarray(np.where(self.label == 1, label_weights[1],
                                       label_weights[0]), dtype=jnp.float32),
        })

    def get_gradients(self, score):
        return self._grad(score)


class MulticlassLogloss(ObjectiveFunction):
    """Softmax multiclass (multiclass_objective.hpp:13-94)."""

    name = "multiclass"

    def __init__(self, config):
        self.num_class = int(config.num_class)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label_int = self.label.astype(np.int32)
        if label_int.min() < 0 or label_int.max() >= self.num_class:
            Log.fatal("Label must be in [0, %d), but found %d in label",
                      self.num_class, int(label_int.min() if label_int.min() < 0
                                          else label_int.max()))
        def _grad_pure(ops, score):
            p = jax.nn.softmax(score, axis=0)  # (K, N)
            g = p - ops["onehot"]
            h = 2.0 * p * (1.0 - p)
            weights = ops.get("weights")
            if weights is not None:
                g = g * weights[None, :]
                h = h * weights[None, :]
            return g, h

        self._install_grad(_grad_pure, {"onehot": jnp.asarray(
            np.eye(self.num_class, dtype=np.float32)[label_int].T)})  # (K, N)

    def get_gradients(self, score):
        return self._grad(score)


class LambdarankNDCG(ObjectiveFunction):
    """LambdaRank with NDCG weighting (rank_objective.hpp:19-227).

    Gradients run ON DEVICE via the padded-query pairwise kernel
    (rank_device.py) — `self._grad` is the jitted function, which also
    makes lambdarank eligible for the fused multi-iteration trainer.
    The float64 host path below is kept as the accuracy reference
    (tests pin the two against each other).
    """

    name = "lambdarank"

    def __init__(self, config):
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0.0:
            Log.fatal("Sigmoid param %f should be greater than zero", self.sigmoid)
        self.label_gain = np.asarray(config.label_gain, dtype=np.float64)
        self.optimize_pos_at = int(config.max_position)
        self.min_input = -50.0 / self.sigmoid / 2.0
        self.max_input = -self.min_input

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        from ..metrics.dcg_calculator import DCGCalculator
        self.dcg = DCGCalculator(self.label_gain)
        if metadata.query_boundaries is None:
            Log.fatal("Lambdarank tasks require query information")
        self.query_boundaries = np.asarray(metadata.query_boundaries)
        self.num_queries = len(self.query_boundaries) - 1
        self.inverse_max_dcgs = np.zeros(self.num_queries)
        for q in range(self.num_queries):
            lo, hi = self.query_boundaries[q], self.query_boundaries[q + 1]
            maxdcg = self.dcg.cal_maxdcg_at_k(self.optimize_pos_at, self.label[lo:hi])
            self.inverse_max_dcgs[q] = 1.0 / maxdcg if maxdcg > 0 else 0.0
        from .rank_device import PaddedQueryLayout, make_lambdarank_gradfn
        self.layout = PaddedQueryLayout(self.query_boundaries, num_data)
        self._grad = make_lambdarank_gradfn(
            self.layout, self.label, self.label_gain, self.sigmoid,
            self.optimize_pos_at, self.weights)

    def get_gradients(self, score):
        return self._grad(jnp.asarray(score, dtype=jnp.float32).reshape(1, -1))

    def _sigmoid(self, x):
        x = np.clip(x, self.min_input, self.max_input)
        return 2.0 / (1.0 + np.exp(2.0 * x * self.sigmoid))

    def get_gradients_host(self, score):
        score = np.asarray(score, dtype=np.float32).reshape(-1)
        grad = np.zeros_like(score, dtype=np.float64)
        hess = np.zeros_like(score, dtype=np.float64)
        discount = self.dcg.discount
        for q in range(self.num_queries):
            lo, hi = self.query_boundaries[q], self.query_boundaries[q + 1]
            cnt = hi - lo
            if cnt <= 1:
                continue
            s = score[lo:hi].astype(np.float64)
            lab = self.label[lo:hi].astype(np.int64)
            inv_max_dcg = self.inverse_max_dcgs[q]
            order = np.argsort(-s, kind="stable")
            rank_of = np.empty(cnt, dtype=np.int64)
            rank_of[order] = np.arange(cnt)
            best = s[order[0]]
            worst_idx = cnt - 1
            if worst_idx > 0 and s[order[worst_idx]] == K_MIN_SCORE:
                worst_idx -= 1
            worst = s[order[worst_idx]]

            # pair matrix: i = high (larger label), j = low
            lg = self.label_gain[lab]
            dcg_gap = lg[:, None] - lg[None, :]                   # >0 when i higher
            pair_mask = dcg_gap > 0
            disc = discount[np.minimum(rank_of, len(discount) - 1)]
            paired_discount = np.abs(disc[:, None] - disc[None, :])
            delta_ndcg = dcg_gap * paired_discount * inv_max_dcg
            delta_score = s[:, None] - s[None, :]
            if best != worst:
                delta_ndcg = delta_ndcg / (0.01 + np.abs(delta_score))
            p_lambda = self._sigmoid(delta_score)
            p_hess = p_lambda * (2.0 - p_lambda)
            lam = -p_lambda * delta_ndcg * pair_mask
            hes = 2.0 * p_hess * delta_ndcg * pair_mask
            g = lam.sum(axis=1) - lam.sum(axis=0)
            h = hes.sum(axis=1) + hes.sum(axis=0)
            if self.weights is not None:
                g *= self.weights[lo:hi]
                h *= self.weights[lo:hi]
            grad[lo:hi] = g
            hess[lo:hi] = h
        import jax.numpy as jnp
        return (jnp.asarray(grad[None, :], dtype=jnp.float32),
                jnp.asarray(hess[None, :], dtype=jnp.float32))


def create_objective(name, config):
    """Factory (objective_function.cpp:9-20). Returns None for unknown names
    (the C API allows training with custom objectives and objective=none)."""
    name = str(name).lower()
    if name == "regression":
        return RegressionL2loss()
    if name == "binary":
        return BinaryLogloss(config)
    if name == "multiclass":
        return MulticlassLogloss(config)
    if name == "lambdarank":
        return LambdarankNDCG(config)
    if name in ("none", ""):
        return None
    Log.fatal("Unknown objective type name: %s", name)
