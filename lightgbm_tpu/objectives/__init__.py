"""Objective functions: gradients/hessians from scores.

Reference: src/objective/ (regression_objective.hpp, binary_objective.hpp,
multiclass_objective.hpp, rank_objective.hpp), factory
src/objective/objective_function.cpp:9-20.

Scores and gradients are (num_class, N) device arrays; the elementwise
objectives are jitted jnp code. Lambdarank's per-query pairwise pass runs
as padded-batch device code would in a later revision; v1 computes it on
host with fully vectorized numpy per query (the reference is also a
host-side O(n_q^2) loop; this is not the training bottleneck at the
reference's query sizes).
"""

from .objectives import (
    ObjectiveFunction,
    RegressionL2loss,
    BinaryLogloss,
    MulticlassLogloss,
    LambdarankNDCG,
    create_objective,
)

__all__ = ["ObjectiveFunction", "RegressionL2loss", "BinaryLogloss",
           "MulticlassLogloss", "LambdarankNDCG", "create_objective"]
