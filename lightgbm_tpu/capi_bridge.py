"""Python side of the C API shim.

Reference: src/c_api.cpp (the `Booster` wrapper class and the 38
`LGBM_*` exports, c_api.cpp:26-240 and below). The native shim
(src_native/c_api_shim.cpp) embeds CPython and forwards every C call
here with raw pointer addresses; this module does ALL marshalling with
ctypes/numpy and implements the handle objects on top of the public
Python API (basic.Booster / io.dataset.CoreDataset).

Handles passed back to C are plain Python objects; the shim holds a
strong reference until the matching *Free call.
"""

import ctypes

import numpy as np

from .basic import Booster, Dataset
from .config import str2map

C_API_DTYPE_FLOAT32 = 0
C_API_DTYPE_FLOAT64 = 1
C_API_DTYPE_INT32 = 2
C_API_DTYPE_INT64 = 3

C_API_PREDICT_NORMAL = 0
C_API_PREDICT_RAW_SCORE = 1
C_API_PREDICT_LEAF_INDEX = 2

_CTYPES = {
    C_API_DTYPE_FLOAT32: ctypes.c_float,
    C_API_DTYPE_FLOAT64: ctypes.c_double,
    C_API_DTYPE_INT32: ctypes.c_int32,
    C_API_DTYPE_INT64: ctypes.c_int64,
}
_NPTYPES = {
    C_API_DTYPE_FLOAT32: np.float32,
    C_API_DTYPE_FLOAT64: np.float64,
    C_API_DTYPE_INT32: np.int32,
    C_API_DTYPE_INT64: np.int64,
}


def _read_array(addr, dtype_code, n):
    if addr == 0 or n == 0:
        return np.zeros(0, dtype=_NPTYPES[dtype_code])
    buf = (_CTYPES[dtype_code] * n).from_address(addr)
    return np.frombuffer(buf, dtype=_NPTYPES[dtype_code]).copy()


def _write_array(addr, dtype_code, values):
    values = np.asarray(values, dtype=_NPTYPES[dtype_code]).reshape(-1)
    buf = (_CTYPES[dtype_code] * len(values)).from_address(addr)
    buf[:] = values.tolist()
    return len(values)


def _write_scalar(addr, dtype_code, value):
    _CTYPES[dtype_code].from_address(addr).value = value


class _CDataset:
    """DatasetHandle payload: a constructed public Dataset."""

    def __init__(self, dataset: Dataset):
        self.dataset = dataset
        self.dataset.construct()
        self._field_refs = {}

    @property
    def core(self):
        return self.dataset._core


class _CBooster:
    """BoosterHandle payload (the reference's `Booster` wrapper,
    c_api.cpp:26-240)."""

    def __init__(self, booster: Booster, train_cd=None):
        self.booster = booster
        self.train_cd = train_cd
        self.num_valid = 0


def _params_to_dict(parameters):
    return str2map(parameters or "")


# --------------------------------------------------------------- datasets
def dataset_create_from_file(filename, parameters, reference):
    params = _params_to_dict(parameters)
    ref = reference.dataset if reference is not None else None
    ds = Dataset(filename, reference=ref, params=params, free_raw_data=False)
    return _CDataset(ds)


def dataset_create_from_mat(data_addr, data_type, nrow, ncol, is_row_major,
                            parameters, reference):
    flat = _read_array(data_addr, data_type, nrow * ncol)
    mat = flat.reshape((nrow, ncol) if is_row_major else (ncol, nrow))
    if not is_row_major:
        mat = mat.T
    params = _params_to_dict(parameters)
    ref = reference.dataset if reference is not None else None
    ds = Dataset(np.ascontiguousarray(mat, dtype=np.float32),
                 reference=ref, params=params, free_raw_data=False)
    return _CDataset(ds)


def dataset_create_from_csr(indptr_addr, indptr_type, indices_addr, data_addr,
                            data_type, nindptr, nelem, num_col, parameters,
                            reference):
    """Sparse rows stay sparse until binning (c_api.cpp:317-376): the
    CSR triplets transpose to a column source in O(nnz) and each column
    densifies one at a time inside the loader."""
    from .io.dataset import CscColumns
    indptr = _read_array(indptr_addr, indptr_type, nindptr)
    indices = _read_array(indices_addr, C_API_DTYPE_INT32, nelem)
    vals = _read_array(data_addr, data_type, nelem)
    src = CscColumns.from_csr(indptr, indices, vals, num_col)
    params = _params_to_dict(parameters)
    ref = reference.dataset if reference is not None else None
    return _CDataset(Dataset(src, reference=ref, params=params,
                             free_raw_data=False))


def dataset_create_from_csc(colptr_addr, colptr_type, indices_addr, data_addr,
                            data_type, ncolptr, nelem, num_row, parameters,
                            reference):
    """Column-major sparse input binned without densifying
    (c_api.cpp:378-427)."""
    from .io.dataset import CscColumns
    colptr = _read_array(colptr_addr, colptr_type, ncolptr)
    indices = _read_array(indices_addr, C_API_DTYPE_INT32, nelem)
    vals = _read_array(data_addr, data_type, nelem)
    src = CscColumns(colptr, indices, vals, num_row, ncolptr - 1)
    params = _params_to_dict(parameters)
    ref = reference.dataset if reference is not None else None
    return _CDataset(Dataset(src, reference=ref, params=params,
                             free_raw_data=False))


def dataset_get_subset(cd, indices_addr, num_indices, parameters):
    indices = _read_array(indices_addr, C_API_DTYPE_INT32, num_indices)
    sub = cd.dataset.subset(indices, params=_params_to_dict(parameters))
    return _CDataset(sub)


def dataset_set_feature_names(cd, names):
    cd.dataset.set_feature_name(list(names))


def dataset_save_binary(cd, filename):
    cd.dataset.save_binary(filename)


def dataset_set_field(cd, field_name, data_addr, num_element, dtype_code):
    arr = _read_array(data_addr, dtype_code, num_element)
    meta = cd.core.metadata
    if field_name == "label":
        meta.set_label(arr.astype(np.float32))
    elif field_name == "weight":
        meta.set_weights(arr.astype(np.float32))
    elif field_name == "group" or field_name == "query":
        meta.set_query(arr.astype(np.int64))
    elif field_name == "init_score":
        meta.set_init_score(arr.astype(np.float64))
    else:
        raise ValueError(f"Unknown field name: {field_name}")


def dataset_get_field(cd, field_name, out_len_addr, out_ptr_addr,
                      out_type_addr):
    meta = cd.core.metadata
    if field_name == "label":
        arr, code = meta.label, C_API_DTYPE_FLOAT32
        arr = None if arr is None else np.asarray(arr, np.float32)
    elif field_name == "weight":
        arr, code = meta.weights, C_API_DTYPE_FLOAT32
        arr = None if arr is None else np.asarray(arr, np.float32)
    elif field_name == "group" or field_name == "query":
        qb = meta.query_boundaries
        arr = None if qb is None else np.diff(qb).astype(np.int32)
        code = C_API_DTYPE_INT32
    elif field_name == "init_score":
        arr = meta.init_score
        arr = None if arr is None else np.asarray(arr, np.float64)
        code = C_API_DTYPE_FLOAT64
    else:
        raise ValueError(f"Unknown field name: {field_name}")
    if arr is None:
        _write_scalar(out_len_addr, C_API_DTYPE_INT64, 0)
        _write_scalar(out_ptr_addr, C_API_DTYPE_INT64, 0)
        _write_scalar(out_type_addr, C_API_DTYPE_INT32, code)
        return
    arr = np.ascontiguousarray(arr)
    cd._field_refs[field_name] = arr  # keep alive while C reads it
    _write_scalar(out_len_addr, C_API_DTYPE_INT64, len(arr))
    _write_scalar(out_ptr_addr, C_API_DTYPE_INT64,
                  arr.ctypes.data)
    _write_scalar(out_type_addr, C_API_DTYPE_INT32, code)


def dataset_get_num_data(cd):
    return cd.core.num_data


def dataset_get_num_feature(cd):
    return cd.core.num_features


# --------------------------------------------------------------- boosters
def booster_create(train_cd, parameters):
    params = _params_to_dict(parameters)
    booster = Booster(params=params, train_set=train_cd.dataset)
    return _CBooster(booster, train_cd)


def booster_create_from_modelfile(filename, out_num_iterations_addr):
    booster = Booster(model_file=filename)
    _write_scalar(out_num_iterations_addr, C_API_DTYPE_INT64,
                  booster.current_iteration())
    return _CBooster(booster)


def booster_merge(cb, other_cb):
    cb.booster.gbdt.merge_from(other_cb.booster.gbdt)


def booster_add_valid_data(cb, valid_cd):
    cb.num_valid += 1
    valid_cd.dataset._predictor = cb.booster._Booster__init_predictor \
        if hasattr(cb.booster, "_Booster__init_predictor") else None
    cb.booster.add_valid(valid_cd.dataset, f"valid_{cb.num_valid}")


def booster_reset_training_data(cb, train_cd):
    cb.booster.update(train_set=train_cd.dataset)
    cb.train_cd = train_cd


def booster_reset_parameter(cb, parameters):
    cb.booster.reset_parameter(_params_to_dict(parameters))


def booster_get_num_classes(cb):
    return cb.booster.gbdt.num_class


def booster_update_one_iter(cb, is_finished_addr):
    finished = cb.booster.gbdt.train_one_iter(is_eval=False)
    _write_scalar(is_finished_addr, C_API_DTYPE_INT32, 1 if finished else 0)


def booster_update_one_iter_custom(cb, grad_addr, hess_addr,
                                   is_finished_addr):
    gbdt = cb.booster.gbdt
    n = gbdt.num_data * gbdt.num_class
    grad = _read_array(grad_addr, C_API_DTYPE_FLOAT32, n)
    hess = _read_array(hess_addr, C_API_DTYPE_FLOAT32, n)
    finished = gbdt.train_one_iter(grad, hess, is_eval=False)
    _write_scalar(is_finished_addr, C_API_DTYPE_INT32, 1 if finished else 0)


def booster_rollback_one_iter(cb):
    cb.booster.rollback_one_iter()


def booster_get_current_iteration(cb):
    return cb.booster.current_iteration()


def booster_get_eval_counts(cb):
    return sum(len(m.names) for m in cb.booster.gbdt.training_metrics)


def booster_get_eval_names(cb, out_strs_addr):
    """Writes each name into the caller's pre-allocated char* slots
    (the reference python wrapper allocates 255-byte buffers)."""
    names = cb.booster.gbdt.get_eval_names(0)
    # Read the char** as raw pointer values: indexing a c_char_p array
    # yields a *copied* bytes object, so memmove through it would write
    # into the copy, never the caller's buffers.
    ptrs = (ctypes.c_void_p * max(len(names), 1)).from_address(out_strs_addr)
    for i, name in enumerate(names):
        raw = name.encode() + b"\0"
        ctypes.memmove(ptrs[i], raw, len(raw))
    return len(names)


def booster_get_eval(cb, data_idx, out_results_addr):
    vals = cb.booster.gbdt.get_eval_at(data_idx)
    return _write_array(out_results_addr, C_API_DTYPE_FLOAT32, vals)


def booster_get_predict(cb, data_idx, out_result_addr):
    vals = cb.booster.gbdt.get_predict_at(data_idx)
    return _write_array(out_result_addr, C_API_DTYPE_FLOAT32, vals)


def _predict_matrix(cb, mat, predict_type, num_iteration):
    gbdt = cb.booster.gbdt
    if predict_type == C_API_PREDICT_LEAF_INDEX:
        out = gbdt.predict_leaf_index(mat, num_iteration)
    elif predict_type == C_API_PREDICT_RAW_SCORE:
        out = gbdt.predict_raw(mat, num_iteration)
    else:
        out = gbdt.predict(mat, num_iteration)
    return np.asarray(out, dtype=np.float64).reshape(-1)


def booster_predict_for_file(cb, data_filename, data_has_header,
                             predict_type, num_iteration, result_filename):
    from .application import Predictor
    predictor = Predictor(
        cb.booster.gbdt,
        is_raw_score=predict_type == C_API_PREDICT_RAW_SCORE,
        is_predict_leaf_index=predict_type == C_API_PREDICT_LEAF_INDEX,
        num_iteration=num_iteration)
    predictor.predict_file(data_filename, result_filename,
                           has_header=bool(data_has_header))


def booster_predict_for_mat(cb, data_addr, data_type, nrow, ncol,
                            is_row_major, predict_type, num_iteration,
                            out_len_addr, out_result_addr):
    flat = _read_array(data_addr, data_type, nrow * ncol)
    mat = flat.reshape((nrow, ncol) if is_row_major else (ncol, nrow))
    if not is_row_major:
        mat = mat.T
    out = _predict_matrix(cb, np.ascontiguousarray(mat), predict_type,
                          num_iteration)
    n = _write_array(out_result_addr, C_API_DTYPE_FLOAT64, out)
    _write_scalar(out_len_addr, C_API_DTYPE_INT64, n)


def booster_predict_for_csr(cb, indptr_addr, indptr_type, indices_addr,
                            data_addr, data_type, nindptr, nelem, num_col,
                            predict_type, num_iteration, out_len_addr,
                            out_result_addr):
    indptr = _read_array(indptr_addr, indptr_type, nindptr)
    indices = _read_array(indices_addr, C_API_DTYPE_INT32, nelem)
    vals = _read_array(data_addr, data_type, nelem)
    nrow = nindptr - 1
    ncol = num_col if num_col > 0 else (int(indices.max()) + 1 if nelem else 0)
    mat = np.zeros((nrow, ncol), dtype=np.float64)
    for i in range(nrow):
        sl = slice(indptr[i], indptr[i + 1])
        mat[i, indices[sl]] = vals[sl]
    out = _predict_matrix(cb, mat, predict_type, num_iteration)
    n = _write_array(out_result_addr, C_API_DTYPE_FLOAT64, out)
    _write_scalar(out_len_addr, C_API_DTYPE_INT64, n)


def booster_save_model(cb, num_iteration, filename):
    cb.booster.save_model(filename, num_iteration)


def booster_dump_model(cb, buffer_len, out_len_addr, out_str_addr):
    """out_str_addr is the caller's pre-allocated char buffer; out_len is
    always written so the caller can re-allocate and retry."""
    dumped = cb.booster.dump_model().encode() + b"\0"
    _write_scalar(out_len_addr, C_API_DTYPE_INT64, len(dumped))
    if len(dumped) <= buffer_len and out_str_addr:
        ctypes.memmove(out_str_addr, dumped, len(dumped))


def booster_get_leaf_value(cb, tree_idx, leaf_idx, out_val_addr):
    tree = cb.booster.gbdt.models[tree_idx]
    _write_scalar(out_val_addr, C_API_DTYPE_FLOAT32,
                  float(tree.leaf_value[leaf_idx]))


def booster_set_leaf_value(cb, tree_idx, leaf_idx, val):
    # In-place Tree mutation bypasses _VersionedList's mutation counter;
    # bump it so the (n_used, len, version)-keyed prediction caches
    # (_stack_cache / _dev_model_cache) can't serve the pre-edit model.
    cb.booster.gbdt.models[tree_idx].leaf_value[leaf_idx] = float(val)
    cb.booster.gbdt.models._bump()
