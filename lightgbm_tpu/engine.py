"""Training entry points: `train` and `cv`.

Reference: python-package/lightgbm/engine.py:12-395. Same control flow:
predictor chaining for init_model, valid-set reference alignment,
callback orchestration (before/after each iteration, ordered), early
stopping via EarlyStopException, and n-fold CV built on Dataset.subset
with mean/std aggregation.
"""

import collections
from operator import attrgetter

import numpy as np

from . import callback
from .basic import Booster, Dataset, LightGBMError, _InnerPredictor, is_str


def _configure_callbacks(callbacks):
    """Normalize user callbacks: default ordering, split into before/after
    iteration groups, sorted by `.order` (engine.py:124-150)."""
    if callbacks is None:
        callbacks = set()
    else:
        for i, cb in enumerate(callbacks):
            cb.__dict__.setdefault("order", i - len(callbacks))
        callbacks = set(callbacks)
    return callbacks


def _split_callbacks(callbacks):
    before = {cb for cb in callbacks if getattr(cb, "before_iteration", False)}
    after = callbacks - before
    return (sorted(before, key=attrgetter("order")),
            sorted(after, key=attrgetter("order")))


def _train_blockwise(booster, callbacks_after_iter, init_iteration,
                     num_boost_round, is_valid_contain_train, feval,
                     early_stopping_rounds, ckpt_cbs=(), start_offset=0):
    """Fused multi-iteration training with per-iteration callback
    replay (see the blockwise comment in train()). Each block is ONE
    device program (gbdt.train_many_eval); metric values for every
    iteration inside the block come from device-computed score
    snapshots. An early-stop break mid-block drops the overshoot
    trees scorelessly — the snapshot already IS the kept state.

    Checkpoint callbacks (`ckpt_cbs`) fire only at BLOCK boundaries:
    mid-block the model list already holds the whole block's trees, so
    a mid-block snapshot would capture the future. The block size is
    clamped (and boundaries aligned) to the snapshot cadence so every
    cadence point is a block boundary."""
    gbdt = booster.gbdt
    end = init_iteration + num_boost_round
    # overshoot past the true stopping round costs at most block-1
    # wasted iterations, so tie the block to the early-stop patience
    if early_stopping_rounds is None:
        block_full = num_boost_round
    else:
        block_full = min(num_boost_round,
                         max(5, min(int(early_stopping_rounds), 25)))
    snap_period = min((cb.period for cb in ckpt_cbs if cb.period > 0),
                      default=0)
    if snap_period:
        block_full = max(1, min(block_full, snap_period))

    def fire_checkpoints(i):
        for cb in ckpt_cbs:
            cb(callback.CallbackEnv(
                model=booster, cvfolds=None, iteration=i,
                begin_iteration=init_iteration, end_iteration=end,
                evaluation_result_list=[]))

    def run_callbacks(i):
        """One iteration's eval + after-iteration callbacks against the
        CURRENT scores. Returns True on EarlyStopException."""
        evaluation_result_list = []
        if is_valid_contain_train:
            evaluation_result_list.extend(booster.eval_train(feval))
        evaluation_result_list.extend(booster.eval_valid(feval))
        try:
            for cb in callbacks_after_iter:
                cb(callback.CallbackEnv(
                    model=booster, cvfolds=None, iteration=i,
                    begin_iteration=init_iteration, end_iteration=end,
                    evaluation_result_list=evaluation_result_list))
        except callback.EarlyStopException:
            return True
        return False

    i = init_iteration + start_offset
    while i < end:
        step = min(block_full, end - i)
        if snap_period:
            # align boundaries to the cadence (a resume can start the
            # loop off-cadence only if the newest snapshot did)
            boundary = ((gbdt.iter // snap_period) + 1) * snap_period
            step = min(step, max(1, boundary - gbdt.iter))
        t_eff, snap = gbdt.train_many_eval(step)
        for t in range(t_eff):
            snap.set_scores_at(t, with_train=is_valid_contain_train)
            if run_callbacks(i + t):
                snap.set_scores_at(t, with_train=True)
                snap.drop_tail_to(t)
                return
        if snap.finalize():
            # natural stop (an empty tree mid-block). The per-iteration
            # path this replay must match does NOT end here: the
            # reference python API ignores update()'s is-finished flag
            # and keeps calling it — evals repeat, and per-iteration
            # sampling (or multiclass gradient coupling) can resume
            # real splitting. First replay the stop iteration's
            # callbacks (its partial-class trees are already applied to
            # the scores), then hand the remaining rounds to the true
            # per-iteration loop.
            i += t_eff
            if i < end and run_callbacks(i):
                return
            i += 1
            while i < end:
                booster.update()
                if run_callbacks(i):
                    return
                i += 1
            return
        i += t_eff
        fire_checkpoints(i - 1)


def train(params, train_set, num_boost_round=100,
          valid_sets=None, valid_names=None,
          fobj=None, feval=None, init_model=None,
          feature_name=None, categorical_feature=None,
          early_stopping_rounds=None, evals_result=None,
          verbose_eval=True, learning_rates=None, callbacks=None,
          resume_from=None):
    """Train one booster (engine.py:12-191). Returns the Booster with
    `best_iteration` set when early stopping fired.

    resume_from: a checkpoint directory (or CheckpointManager) written
    by `callback.checkpoint(...)`. When it holds a valid snapshot, full
    training state (trees, scores, sampling RNG, early-stop trackers,
    eval history) is restored and the loop continues from the
    snapshot's iteration — producing the bit-identical model string of
    an uninterrupted run with the same params and data. No valid
    snapshot = a normal cold start."""
    if is_str(init_model):
        predictor = _InnerPredictor(model_file=init_model)
    elif isinstance(init_model, Booster):
        predictor = init_model._to_predictor()
    else:
        predictor = None
    init_iteration = predictor.num_total_iteration if predictor is not None else 0

    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")
    train_set._set_predictor(predictor)
    train_set.set_feature_name(feature_name)
    train_set.set_categorical_feature(categorical_feature)

    is_valid_contain_train = False
    train_data_name = "training"
    reduced_valid_sets = []
    name_valid_sets = []
    if valid_sets is not None:
        if isinstance(valid_sets, Dataset):
            valid_sets = [valid_sets]
        if isinstance(valid_names, str):
            valid_names = [valid_names]
        for i, valid_data in enumerate(valid_sets):
            if valid_data is train_set:
                is_valid_contain_train = True
                if valid_names is not None and len(valid_names) > i:
                    train_data_name = valid_names[i]
                continue
            if not isinstance(valid_data, Dataset):
                raise TypeError("Training only accepts Dataset object")
            valid_data.set_reference(train_set)
            reduced_valid_sets.append(valid_data)
            if valid_names is not None and len(valid_names) > i:
                name_valid_sets.append(valid_names[i])
            else:
                name_valid_sets.append("valid_" + str(i))

    callbacks = _configure_callbacks(callbacks)
    default_print_cb = early_stop_cb = record_cb = None
    if verbose_eval is True:
        default_print_cb = callback.print_evaluation()
        callbacks.add(default_print_cb)
    elif isinstance(verbose_eval, int) and not isinstance(verbose_eval, bool):
        default_print_cb = callback.print_evaluation(verbose_eval)
        callbacks.add(default_print_cb)
    if early_stopping_rounds is not None:
        early_stop_cb = callback.early_stopping(
            early_stopping_rounds, verbose=bool(verbose_eval))
        callbacks.add(early_stop_cb)
    if learning_rates is not None:
        callbacks.add(callback.reset_parameter(learning_rate=learning_rates))
    if evals_result is not None:
        record_cb = callback.record_evaluation(evals_result)
        callbacks.add(record_cb)
    callbacks_before_iter, callbacks_after_iter = _split_callbacks(callbacks)

    booster = Booster(params=params, train_set=train_set)
    # late-bind the supervisor heartbeat's progress source: an embedder
    # that enabled heartbeats (parallel/heartbeat.py configure) gets
    # per-iteration liveness from this booster; no-op otherwise. Weakly
    # referenced: the process-lifetime service must not keep a dropped
    # booster (dataset bins, score arrays) alive after train() returns.
    import weakref
    from .parallel import heartbeat
    gbdt_ref = weakref.ref(booster.gbdt)

    def _iteration_source():
        gbdt = gbdt_ref()
        return gbdt.iter if gbdt is not None else -1

    heartbeat.bind_iteration_source(_iteration_source)
    if is_valid_contain_train:
        booster.set_train_data_name(train_data_name)
    for valid_set, name_valid_set in zip(reduced_valid_sets, name_valid_sets):
        booster.add_valid(valid_set, name_valid_set)

    all_cbs = callbacks_before_iter + callbacks_after_iter
    ckpt_cbs = [cb for cb in callbacks_after_iter
                if getattr(cb, "is_checkpoint", False)]
    for cb in ckpt_cbs:
        cb.bind_peers(all_cbs)
    # resume: restore the newest valid snapshot (trees, score arrays,
    # RNG streams, callback state) and skip the already-trained rounds
    start_offset = 0
    if resume_from is not None:
        from .utils.checkpoint import CheckpointManager
        manager = (resume_from if isinstance(resume_from, CheckpointManager)
                   else CheckpointManager(resume_from))
        state, _ = manager.load_latest()
        if state is not None:
            restorer = ckpt_cbs[0] if ckpt_cbs \
                else callback._Checkpoint(manager, 0)
            restorer.restore_into(booster, state, all_cbs)
            start_offset = min(booster.gbdt.iter, num_boost_round)
            if booster.gbdt.journal is not None:
                # the restart lands in the run journal's timeline next
                # to the abort that caused it (docs/Observability.md)
                booster.gbdt.journal.event(
                    "resume", iteration=int(booster.gbdt.iter))

    # fast path: nothing needs the per-round boundary (no callbacks, no
    # custom objective, no valid evaluation) — run the whole block as
    # the fused device scan (gbdt.train_many); semantics are identical
    # (parity pinned by tests/test_core_training.py and the fused GOSS/
    # bagging tests). The default print_evaluation callback is exempt:
    # with no valid sets its evaluation list is always empty and it
    # prints nothing (callback.py). Checkpoint callbacks are exempt
    # too: the scan is chopped into cadence-sized blocks with a
    # snapshot between blocks (same trees — block size only moves the
    # host-sync points).
    effective_after = [cb for cb in callbacks_after_iter
                       if cb is not default_print_cb and cb not in ckpt_cbs]
    if (not callbacks_before_iter and not effective_after
            and fobj is None and valid_sets is None
            and getattr(booster.gbdt, "_fused_eligible", lambda: False)()):
        periods = [cb.period for cb in ckpt_cbs if cb.period > 0]
        if periods:
            block = min(periods)
            stopped = False
            while booster.gbdt.iter < num_boost_round and not stopped:
                # align block boundaries to the cadence (a resume can
                # start off-cadence; fixed-size steps would then never
                # land on a snapshot point again)
                boundary = ((booster.gbdt.iter // block) + 1) * block
                step = min(boundary - booster.gbdt.iter,
                           num_boost_round - booster.gbdt.iter)
                stopped = booster.gbdt.train_many(step)
                for cb in ckpt_cbs:
                    cb(callback.CallbackEnv(
                        model=booster, cvfolds=None,
                        iteration=init_iteration + booster.gbdt.iter - 1,
                        begin_iteration=init_iteration,
                        end_iteration=init_iteration + num_boost_round,
                        evaluation_result_list=[]))
        elif num_boost_round > start_offset:
            booster.gbdt.train_many(num_boost_round - start_offset)
        booster.best_iteration = num_boost_round
        return booster

    # blockwise fused path (valid sets and/or early stopping present):
    # every callback here is one this function itself created from a
    # kwarg, so the per-iteration callback protocol can be REPLAYED
    # after a fused multi-iteration device block from per-iteration
    # score snapshots (gbdt.train_many_eval) — observable behavior
    # (eval values, print cadence, evals_result history, early-stop
    # round, final model) is identical to the per-iteration loop, but
    # tree building never leaves the device mid-block. Custom user
    # callbacks fall back to the true per-iteration loop: they may
    # mutate the booster mid-training.
    engine_created = {cb for cb in (default_print_cb, early_stop_cb,
                                    record_cb) if cb is not None}
    use_blockwise = (
        valid_sets is not None
        and fobj is None
        and not callbacks_before_iter
        and all(cb in engine_created or cb in ckpt_cbs
                for cb in callbacks_after_iter)
        and getattr(booster.gbdt, "_fused_eligible", lambda **_: False)(
            ignore_train_metrics=True))
    if use_blockwise:
        replay_after = [cb for cb in callbacks_after_iter
                        if cb not in ckpt_cbs]
        _train_blockwise(booster, replay_after, init_iteration,
                         num_boost_round, is_valid_contain_train, feval,
                         early_stopping_rounds, ckpt_cbs=ckpt_cbs,
                         start_offset=start_offset)
    else:
        for i in range(init_iteration + start_offset,
                       init_iteration + num_boost_round):
            for cb in callbacks_before_iter:
                cb(callback.CallbackEnv(model=booster, cvfolds=None, iteration=i,
                                        begin_iteration=init_iteration,
                                        end_iteration=init_iteration + num_boost_round,
                                        evaluation_result_list=None))
            booster.update(fobj=fobj)

            evaluation_result_list = []
            if valid_sets is not None:
                if is_valid_contain_train:
                    evaluation_result_list.extend(booster.eval_train(feval))
                evaluation_result_list.extend(booster.eval_valid(feval))
            try:
                for cb in callbacks_after_iter:
                    cb(callback.CallbackEnv(model=booster, cvfolds=None, iteration=i,
                                            begin_iteration=init_iteration,
                                            end_iteration=init_iteration + num_boost_round,
                                            evaluation_result_list=evaluation_result_list))
            except callback.EarlyStopException:
                break
    if booster.attr("best_iteration") is not None:
        booster.best_iteration = int(booster.attr("best_iteration")) + 1
    else:
        # reference quirk kept (engine.py:190): without early stopping this
        # is num_boost_round, NOT init_iteration + num_boost_round — under
        # continued training predict(best_iteration) then truncates
        booster.best_iteration = num_boost_round
    return booster


class CVBooster:
    """One fold of CV (engine.py:194-209)."""

    def __init__(self, train_set, valid_test, params):
        self.train_set = train_set
        self.valid_test = valid_test
        self.booster = Booster(params=params, train_set=train_set)
        self.booster.add_valid(valid_test, "valid")

    def update(self, fobj):
        self.booster.update(fobj=fobj)

    def eval(self, feval):
        return self.booster.eval_valid(feval)


def _make_n_folds(full_data, nfold, params, seed, fpreproc=None,
                  stratified=False, shuffle=True):
    """engine.py:221-249."""
    np.random.seed(seed)
    if stratified:
        try:
            from sklearn.model_selection import StratifiedKFold
        except ImportError:
            raise LightGBMError("Scikit-learn is required for stratified cv")
        sfk = StratifiedKFold(n_splits=nfold, shuffle=shuffle, random_state=seed)
        idset = [x[1] for x in sfk.split(X=full_data.get_label(),
                                         y=full_data.get_label())]
    else:
        full_data.construct()
        n = full_data.num_data()
        randidx = np.random.permutation(n) if shuffle else np.arange(n)
        # reference quirk kept (engine.py:236-237): the last n % nfold rows
        # of the permutation appear in no fold
        kstep = int(len(randidx) / nfold)
        idset = [randidx[(i * kstep): min(len(randidx), (i + 1) * kstep)]
                 for i in range(nfold)]

    ret = []
    for k in range(nfold):
        train_set = full_data.subset(
            np.concatenate([idset[i] for i in range(nfold) if k != i]))
        valid_set = full_data.subset(idset[k])
        if fpreproc is not None:
            train_set, valid_set, tparam = fpreproc(train_set, valid_set,
                                                    params.copy())
        else:
            tparam = params
        ret.append(CVBooster(train_set, valid_set, tparam))
    return ret


def _agg_cv_result(raw_results):
    """engine.py:251-261."""
    cvmap = collections.defaultdict(list)
    metric_type = {}
    for one_result in raw_results:
        for one_line in one_result:
            metric_type[one_line[1]] = one_line[3]
            cvmap[one_line[1]].append(one_line[2])
    return [("cv_agg", k, np.mean(v), metric_type[k], np.std(v))
            for k, v in cvmap.items()]


def cv(params, train_set, num_boost_round=10, nfold=5, stratified=False,
       shuffle=True, metrics=None, fobj=None, feval=None, init_model=None,
       feature_name=None, categorical_feature=None,
       early_stopping_rounds=None, fpreproc=None,
       verbose_eval=None, show_stdv=True, seed=0, callbacks=None):
    """Cross-validation (engine.py:263-395). Returns a dict
    {metric-mean: [...], metric-stdv: [...]}."""
    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")

    if is_str(init_model):
        predictor = _InnerPredictor(model_file=init_model)
    elif isinstance(init_model, Booster):
        predictor = init_model._to_predictor()
    else:
        predictor = None
    train_set._set_predictor(predictor)
    train_set.set_feature_name(feature_name)
    train_set.set_categorical_feature(categorical_feature)

    params = dict(params)
    if metrics:
        existing = params.get("metric", []) or []
        metric_list = existing.split(",") if is_str(existing) else list(existing)
        if is_str(metrics):
            metric_list.append(metrics)
        else:
            metric_list.extend(metrics)
        params["metric"] = metric_list

    results = collections.defaultdict(list)
    cvfolds = _make_n_folds(train_set, nfold, params, seed, fpreproc,
                            stratified, shuffle)

    callbacks = _configure_callbacks(callbacks)
    if early_stopping_rounds is not None:
        callbacks.add(callback.early_stopping(early_stopping_rounds,
                                              verbose=False))
    if verbose_eval is True:
        callbacks.add(callback.print_evaluation(show_stdv=show_stdv))
    elif isinstance(verbose_eval, int) and not isinstance(verbose_eval, bool):
        callbacks.add(callback.print_evaluation(verbose_eval,
                                                show_stdv=show_stdv))
    callbacks_before_iter, callbacks_after_iter = _split_callbacks(callbacks)

    for i in range(num_boost_round):
        for cb in callbacks_before_iter:
            cb(callback.CallbackEnv(model=None, cvfolds=cvfolds, iteration=i,
                                    begin_iteration=0,
                                    end_iteration=num_boost_round,
                                    evaluation_result_list=None))
        for fold in cvfolds:
            fold.update(fobj)
        res = _agg_cv_result([f.eval(feval) for f in cvfolds])
        for _, key, mean, _, std in res:
            results[key + "-mean"].append(mean)
            results[key + "-stdv"].append(std)
        try:
            for cb in callbacks_after_iter:
                cb(callback.CallbackEnv(model=None, cvfolds=cvfolds, iteration=i,
                                        begin_iteration=0,
                                        end_iteration=num_boost_round,
                                        evaluation_result_list=res))
        except callback.EarlyStopException as e:
            for k in results:
                results[k] = results[k][:e.best_iteration + 1]
            break
    return dict(results)
