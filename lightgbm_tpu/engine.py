def train(*a, **k): raise NotImplementedError
def cv(*a, **k): raise NotImplementedError
