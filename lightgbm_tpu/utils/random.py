"""Seeded RNG mirroring the reference's mt19937 wrapper.

Reference: include/LightGBM/utils/random.h:14-73. Backed by numpy's MT19937
(the same core generator); the draw order of `uniform_int_distribution` is
implementation-defined in C++, so exact bit-parity with a given libstdc++ is
not guaranteed — the *algorithms* (sequential K-of-N selection sampling,
bagging probabilities) are identical.
"""

import numpy as np


class Random:
    def __init__(self, seed=None):
        if seed is None:
            self._rng = np.random.RandomState()
        else:
            self._rng = np.random.RandomState(seed & 0xFFFFFFFF)

    def next_int(self, lower: int, upper: int) -> int:
        """Random integer in [lower, upper)."""
        return int(self._rng.randint(lower, upper))

    def next_double(self) -> float:
        """Random float in [0, 1)."""
        return float(self._rng.random_sample())

    def sample(self, n: int, k: int) -> np.ndarray:
        """K ordered samples from {0..N-1} (random.h:55-68).

        The reference's sequential selection sampling is an O(N) scalar
        loop; sampling the k smallest of N uniform keys draws the same
        uniform-over-k-subsets distribution (and consumes the same N
        draws from the stream) fully vectorized — an 11M-row bin-sample
        is three numpy ops instead of an 11M-iteration Python loop.
        """
        if k > n or k < 0:
            return np.empty(0, dtype=np.int32)
        u = self._rng.random_sample(n)
        if k == n:
            return np.arange(n, dtype=np.int32)
        return np.sort(np.argpartition(u, k)[:k]).astype(np.int32)

    def sample_mask(self, n: int, k: int) -> np.ndarray:
        """Boolean mask variant of `sample`."""
        mask = np.zeros(n, dtype=bool)
        mask[self.sample(n, k)] = True
        return mask
