"""Seeded RNG mirroring the reference's mt19937 wrapper.

Reference: include/LightGBM/utils/random.h:14-73. Backed by numpy's MT19937
(the same core generator); the draw order of `uniform_int_distribution` is
implementation-defined in C++, so exact bit-parity with a given libstdc++ is
not guaranteed — the *algorithms* (sequential K-of-N selection sampling,
bagging probabilities) are identical.
"""

import numpy as np


class Random:
    def __init__(self, seed=None):
        if seed is None:
            self._rng = np.random.RandomState()
        else:
            self._rng = np.random.RandomState(seed & 0xFFFFFFFF)

    def next_int(self, lower: int, upper: int) -> int:
        """Random integer in [lower, upper)."""
        return int(self._rng.randint(lower, upper))

    def next_double(self) -> float:
        """Random float in [0, 1)."""
        return float(self._rng.random_sample())

    def sample(self, n: int, k: int) -> np.ndarray:
        """K ordered samples from {0..N-1} via sequential selection sampling
        (random.h:55-68)."""
        if k > n or k < 0:
            return np.empty(0, dtype=np.int32)
        # vectorized equivalent of the sequential scheme: draw u_i and keep
        # i if u_i < (k - taken) / (n - i). Done in one pass on host.
        u = self._rng.random_sample(n)
        out = []
        taken = 0
        for i in range(n):
            if u[i] < (k - taken) / (n - i):
                out.append(i)
                taken += 1
        return np.asarray(out, dtype=np.int32)

    def sample_mask(self, n: int, k: int) -> np.ndarray:
        """Boolean mask variant of `sample`."""
        mask = np.zeros(n, dtype=bool)
        mask[self.sample(n, k)] = True
        return mask
