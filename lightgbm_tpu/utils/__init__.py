from .log import Log
from .random import Random
from . import common

__all__ = ["Log", "Random", "common"]
