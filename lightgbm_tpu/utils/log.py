"""Leveled logger mirroring the reference's static Log class.

Reference: include/LightGBM/utils/log.h:14-98. Fatal raises (the reference
throws std::runtime_error caught at the CLI / C-API boundary).

Observability extensions (no reference equivalent; the defaults keep
the reference's exact line shape):

- `LIGHTGBM_TPU_LOG_TS=1` (or `Log.enable_timestamps()`): ISO-8601
  timestamps on every line.
- `LIGHTGBM_TPU_LOG_JSON=1`: structured-line mode — each line is one
  JSON object `{"ts","level","msg","rank"}` so supervisor child logs
  are machine-parseable next to the run journal
  (docs/Observability.md).
- rank prefix: injected once `Log.set_rank()` is called (done by
  parallel/distributed.py when jax.distributed comes up), so
  interleaved multi-rank output stays attributable.

The env flags are re-read per line (they are off the hot path; a
supervisor can flip a child's format purely through its environment).
"""

import datetime
import json
import os
import sys


class LightGBMError(Exception):
    """Error raised by the framework (reference: basic.py LightGBMError)."""


class Log:
    # levels: fatal=-1, warning=0, info=1, debug=2
    _level = 1
    _rank = None        # set by set_rank(); None = no rank prefix
    _timestamps = False  # ISO-8601 prefix (or LIGHTGBM_TPU_LOG_TS=1)

    @classmethod
    def reset_log_level(cls, level: int) -> None:
        cls._level = level

    @classmethod
    def set_level_from_verbosity(cls, verbosity: int) -> None:
        # reference: src/io/config.cpp:63-74
        if verbosity == 1:
            cls._level = 1
        elif verbosity == 0:
            cls._level = 0
        elif verbosity >= 2:
            cls._level = 2
        else:
            cls._level = -1

    @classmethod
    def set_rank(cls, rank) -> None:
        """Prefix subsequent lines with `[rank N]` (and a "rank" field
        in JSON mode). Called when jax.distributed initializes
        (parallel/distributed.py); None clears."""
        cls._rank = int(rank) if rank is not None else None

    @classmethod
    def enable_timestamps(cls, on=True) -> None:
        cls._timestamps = bool(on)

    @classmethod
    def debug(cls, fmt, *args):
        if cls._level >= 2:
            cls._write("Debug", fmt, args)

    @classmethod
    def info(cls, fmt, *args):
        if cls._level >= 1:
            cls._write("Info", fmt, args)

    @classmethod
    def warning(cls, fmt, *args):
        if cls._level >= 0:
            cls._write("Warning", fmt, args)

    @classmethod
    def fatal(cls, fmt, *args):
        msg = (fmt % args) if args else str(fmt)
        raise LightGBMError(msg)

    _LEVELS = {"Debug": 2, "Info": 1, "Warning": 0}

    @classmethod
    def structured(cls, level, event, **fields):
        """One machine-attributable record (serving access logs,
        slow-request lines). In LIGHTGBM_TPU_LOG_JSON mode the fields
        merge into the line's JSON object next to ts/level/rank; in
        text mode they render as `event k=v ...`. `level` is "Debug" /
        "Info" / "Warning" and gates like the plain methods."""
        if cls._level < cls._LEVELS.get(level, 1):
            return
        if os.environ.get("LIGHTGBM_TPU_LOG_JSON", "") not in ("", "0"):
            rec = {"ts": datetime.datetime.now().isoformat(
                       timespec="milliseconds"),
                   "level": level, "event": str(event)}
            if cls._rank is not None:
                rec["rank"] = cls._rank
            rec.update(fields)
            sys.stdout.write(json.dumps(rec, default=str) + "\n")
            sys.stdout.flush()
            return
        kv = " ".join(f"{k}={v}" for k, v in fields.items())
        cls._write(level, "%s %s", (event, kv))

    @classmethod
    def _write(cls, level_str, fmt, args):
        msg = (fmt % args) if args else str(fmt)
        if os.environ.get("LIGHTGBM_TPU_LOG_JSON", "") not in ("", "0"):
            rec = {"ts": datetime.datetime.now().isoformat(
                       timespec="milliseconds"),
                   "level": level_str, "msg": msg}
            if cls._rank is not None:
                rec["rank"] = cls._rank
            sys.stdout.write(json.dumps(rec, default=str) + "\n")
            sys.stdout.flush()
            return
        parts = ["[LightGBM-TPU]"]
        if cls._timestamps or os.environ.get("LIGHTGBM_TPU_LOG_TS",
                                             "") not in ("", "0"):
            parts.append("[" + datetime.datetime.now().isoformat(
                timespec="milliseconds") + "]")
        if cls._rank is not None:
            parts.append(f"[rank {cls._rank}]")
        parts.append(f"[{level_str}] {msg}")
        sys.stdout.write(" ".join(parts) + "\n")
        sys.stdout.flush()


def check(condition, msg="check failed"):
    """CHECK macro equivalent (log.h:86-98)."""
    if not condition:
        Log.fatal(msg)
