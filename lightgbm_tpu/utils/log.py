"""Leveled logger mirroring the reference's static Log class.

Reference: include/LightGBM/utils/log.h:14-98. Fatal raises (the reference
throws std::runtime_error caught at the CLI / C-API boundary).
"""

import sys


class LightGBMError(Exception):
    """Error raised by the framework (reference: basic.py LightGBMError)."""


class Log:
    # levels: fatal=-1, warning=0, info=1, debug=2
    _level = 1

    @classmethod
    def reset_log_level(cls, level: int) -> None:
        cls._level = level

    @classmethod
    def set_level_from_verbosity(cls, verbosity: int) -> None:
        # reference: src/io/config.cpp:63-74
        if verbosity == 1:
            cls._level = 1
        elif verbosity == 0:
            cls._level = 0
        elif verbosity >= 2:
            cls._level = 2
        else:
            cls._level = -1

    @classmethod
    def debug(cls, fmt, *args):
        if cls._level >= 2:
            cls._write("Debug", fmt, args)

    @classmethod
    def info(cls, fmt, *args):
        if cls._level >= 1:
            cls._write("Info", fmt, args)

    @classmethod
    def warning(cls, fmt, *args):
        if cls._level >= 0:
            cls._write("Warning", fmt, args)

    @classmethod
    def fatal(cls, fmt, *args):
        msg = (fmt % args) if args else str(fmt)
        raise LightGBMError(msg)

    @staticmethod
    def _write(level_str, fmt, args):
        msg = (fmt % args) if args else str(fmt)
        sys.stdout.write(f"[LightGBM-TPU] [{level_str}] {msg}\n")
        sys.stdout.flush()


def check(condition, msg="check failed"):
    """CHECK macro equivalent (log.h:86-98)."""
    if not condition:
        Log.fatal(msg)
