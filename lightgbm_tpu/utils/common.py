"""String/array helpers mirroring the reference's Common namespace.

Reference: include/LightGBM/utils/common.h:21-397. Fast Atof with na/inf
handling, array<->string converters used by the model text format.
"""

import math

import numpy as np


def atof(s: str) -> float:
    """Parse a double; 'na'/'nan'/'inf' handled (common.h Atof)."""
    s = s.strip()
    if not s:
        return 0.0
    low = s.lower()
    if low in ("na", "nan", "null"):
        return math.nan
    try:
        return float(s)  # handles inf/-inf natively
    except ValueError:
        return math.nan


def atoi(s: str) -> int:
    return int(s.strip())


def array_to_string(arr, sep=" ") -> str:
    """Join array with C++ stream formatting.

    The reference serializes doubles via std::stringstream (6 significant
    digits by default)... except `ArrayToString<double>` uses operator<<
    which gives '%g'-style output. We keep full repr precision for doubles
    to make save->load->predict exact round trips; the reference's loader
    (Common::StringToArray) accepts any float formatting.
    """
    out = []
    for v in arr:
        if isinstance(v, (float, np.floating)):
            if math.isinf(v):
                out.append("inf" if v > 0 else "-inf")
            else:
                out.append(repr(float(v)))
        else:
            out.append(str(int(v)))
    return sep.join(out)


def string_to_array(s: str, dtype, sep=" "):
    parts = [p for p in s.split(sep) if p]
    if dtype is float:
        return np.asarray([atof(p) for p in parts], dtype=np.float64)
    return np.asarray([int(p) for p in parts], dtype=np.int32)


def softmax(x, axis=-1):
    """Stable softmax (common.h:307-322 works on a vector)."""
    x = np.asarray(x, dtype=np.float64)
    m = np.max(x, axis=axis, keepdims=True)
    e = np.exp(x - m)
    return e / np.sum(e, axis=axis, keepdims=True)


def param_dict_to_str(params: dict) -> str:
    """Serialize params the way the reference python package does
    (basic.py:112-144): 'k1=v1 k2=v2', lists joined by ','."""
    if not params:
        return ""
    pairs = []
    for k, v in params.items():
        if isinstance(v, (list, tuple)):
            pairs.append(f"{k}={','.join(map(str, v))}")
        elif isinstance(v, bool):
            pairs.append(f"{k}={'true' if v else 'false'}")
        elif v is None:
            continue
        else:
            pairs.append(f"{k}={v}")
    return " ".join(pairs)
