"""Pre-backend host environment shims (jax-free on purpose).

This module must stay importable and callable BEFORE the XLA CPU
client exists: it edits XLA_FLAGS, which the client reads once at
instantiation. jax imports are fine (client creation is lazy), jax
*use* is not.
"""

import os


def ensure_callback_worker_devices(min_devices=2):
    """On single-CPU hosts, force at least `min_devices` virtual XLA
    host devices so pure_callback always has a worker thread to run on.

    A CPU client built with one device on a one-core machine deadlocks
    host callbacks embedded in async-dispatched programs: the lone
    worker executes the program while the callback's operand delivery
    waits for that same thread (ops/histogram.py
    host_callbacks_hazardous — the PR 14 compacted-learner cliff at
    n > HIST_CHUNK). Two virtual devices cost nothing measurable on the
    rungs this repo gates and clear the hazard entirely.

    Respects an explicit xla_force_host_platform_device_count anywhere
    in XLA_FLAGS (tests pin 8, distributed harnesses pin 2). Returns
    True when the flag was added.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        return False
    try:
        n_cpus = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):  # non-Linux fallback
        n_cpus = os.cpu_count() or 1
    if n_cpus > 1:
        return False
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={min_devices}"
    ).strip()
    return True
