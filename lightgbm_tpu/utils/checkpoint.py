"""Atomic training checkpoints: serialize, rotate, validate, resume.

No reference equivalent — the reference's continued-training path
(`init_model` chaining, engine.py) restarts from a saved model FILE,
which loses the optimizer-side state (score arrays, sampling RNG,
early-stop bookkeeping) and therefore cannot reproduce the uninterrupted
run bit-for-bit. A checkpoint captures the FULL training state (see
models/gbdt.py `capture_training_state`) so `engine.train(...,
resume_from=...)` and the CLI's `snapshot_freq` knob produce the exact
model string an uninterrupted run would have produced.

File format (version 1), one self-contained file per checkpoint:

    LGBMTPUCKPT1\n
    digest=<sha256 hex of payload>\n
    length=<payload byte count>\n
    <payload: npz archive>

The npz payload holds a `meta_json` entry (scalars, strings, callback
state) plus one entry per numpy array (scores, RNG key vector). Writes
are crash-atomic: tmp file in the same directory -> flush -> fsync ->
`os.replace` (plus a best-effort directory fsync), so a kill at any
instant leaves either the old file or the new one, never a torn one.
The loader verifies length and digest and `load_latest` silently falls
back past corrupt/truncated checkpoints to the newest valid one.
Rotation keeps the newest `keep_last_k` files.
"""

import contextlib
import hashlib
import io
import json
import os
import re

import numpy as np

from . import faults
from .log import Log

MAGIC = b"LGBMTPUCKPT1"
_FILE_RE = re.compile(r"^(?P<prefix>.+)\.iter(?P<iter>\d{8})\.ckpt$")


class CheckpointError(Exception):
    """A checkpoint file failed validation (missing/truncated/corrupt)."""


# ------------------------------------------------------------ atomic IO

def _fsync_dir(path):
    """Best-effort fsync of a directory (persists the rename itself)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path, data):
    """Write `data` to `path` crash-atomically: sibling tmp file,
    flush + fsync, `os.replace`, directory fsync. A crash at any point
    leaves either the previous file or the complete new one."""
    with atomic_open(path) as f:
        f.write(data)


def atomic_write_text(path, text):
    atomic_write_bytes(path, text.encode("utf-8"))


@contextlib.contextmanager
def atomic_open(path, mode="wb"):
    """Streaming variant of `atomic_write_bytes`: yields a file handle
    writers can stream into (no in-memory copy of the payload); on
    clean exit the tmp file is fsynced and renamed over `path`, on any
    exception it is removed and the previous file survives."""
    path = os.fspath(path)
    directory = os.path.dirname(os.path.abspath(path))
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, mode) as f:
            yield f
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(directory)


# ------------------------------------------------------- state <-> bytes

def _pack_state(state):
    """Training-state dict -> payload bytes. Arrays become npz entries;
    everything else rides in `meta_json` (floats may be +-inf: Python's
    json emits/accepts Infinity)."""
    arrays = {}
    meta = {}
    for key, value in state.items():
        if isinstance(value, np.ndarray):
            arrays[f"arr_{key}"] = value
        elif (isinstance(value, (list, tuple)) and value
              and all(isinstance(v, np.ndarray) for v in value)):
            meta[f"_arrlist_{key}"] = len(value)
            for i, v in enumerate(value):
                arrays[f"arrlist_{key}_{i}"] = v
        else:
            meta[key] = value
    buf = io.BytesIO()
    np.savez_compressed(buf, meta_json=np.frombuffer(
        json.dumps(meta).encode("utf-8"), dtype=np.uint8), **arrays)
    return buf.getvalue()


def _unpack_state(payload):
    try:
        z = np.load(io.BytesIO(payload), allow_pickle=False)
    except Exception as e:
        raise CheckpointError(f"payload is not a valid archive: {e}")
    if "meta_json" not in z:
        raise CheckpointError("payload missing meta_json")
    meta = json.loads(bytes(z["meta_json"].tobytes()).decode("utf-8"))
    state = {}
    for key, value in meta.items():
        if key.startswith("_arrlist_"):
            name = key[len("_arrlist_"):]
            state[name] = [z[f"arrlist_{name}_{i}"] for i in range(value)]
        else:
            state[key] = value
    for key in z.files:
        if key.startswith("arr_"):
            state[key[len("arr_"):]] = z[key]
    return state


def encode_checkpoint(state):
    """State dict -> full file bytes (header + digest + payload)."""
    payload = _pack_state(state)
    digest = hashlib.sha256(payload).hexdigest()
    header = MAGIC + b"\n" + f"digest={digest}\n".encode("ascii") \
        + f"length={len(payload)}\n".encode("ascii")
    return header + payload


def decode_checkpoint(blob):
    """Full file bytes -> state dict; raises CheckpointError on any
    validation failure (bad magic, short file, digest mismatch)."""
    head, sep, rest = blob.partition(b"\n")
    if head != MAGIC or not sep:
        raise CheckpointError("bad magic (not a lightgbm_tpu checkpoint)")
    dline, sep, rest = rest.partition(b"\n")
    if not sep or not dline.startswith(b"digest="):
        raise CheckpointError("missing digest header")
    lline, sep, payload = rest.partition(b"\n")
    if not sep or not lline.startswith(b"length="):
        raise CheckpointError("missing length header")
    try:
        length = int(lline[len(b"length="):])
    except ValueError:
        raise CheckpointError("unparsable length header")
    if len(payload) != length:
        raise CheckpointError(
            f"truncated payload: {len(payload)} bytes, expected {length}")
    digest = dline[len(b"digest="):].decode("ascii", "replace")
    actual = hashlib.sha256(payload).hexdigest()
    if actual != digest:
        raise CheckpointError(
            f"digest mismatch: header {digest[:12]}.., payload {actual[:12]}..")
    return _unpack_state(payload)


# ---------------------------------------------------------------- manager

class CheckpointManager:
    """Directory of rotated, digest-validated checkpoints.

    Files are `<prefix>.iter<NNNNNNNN>.ckpt`, newest = highest
    iteration. `save` is crash-atomic; `load_latest` returns the newest
    checkpoint that validates, skipping (with a warning) any corrupt or
    truncated ones — so a crash mid-save, a torn disk write, or bit rot
    in the newest file costs at most one snapshot interval of work.
    """

    def __init__(self, directory, keep_last_k=3, prefix="snapshot"):
        self.directory = os.fspath(directory)
        self.keep_last_k = max(1, int(keep_last_k))
        self.prefix = prefix
        os.makedirs(self.directory, exist_ok=True)

    def path_for(self, iteration):
        return os.path.join(self.directory,
                            f"{self.prefix}.iter{int(iteration):08d}.ckpt")

    def checkpoints(self):
        """[(iteration, path)] sorted oldest -> newest."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            m = _FILE_RE.match(name)
            if m and m.group("prefix") == self.prefix:
                out.append((int(m.group("iter")),
                            os.path.join(self.directory, name)))
        out.sort()
        return out

    def save(self, state, iteration):
        """Serialize + atomically write one checkpoint, then rotate.
        Returns the file path."""
        state = dict(state)
        state["checkpoint_iteration"] = int(iteration)
        blob = encode_checkpoint(state)
        # injection point: a "torn write that made it to disk" /
        # bit-rot — the blob is damaged but still lands atomically, so
        # the LOADER's validation is what the test exercises
        blob = faults.mangle_checkpoint_blob(blob)
        path = self.path_for(iteration)
        # injection point: preemption MID-WRITE — half the payload lands
        # in the sibling tmp file and the process dies before the
        # rename, so the previous checkpoint must survive and the
        # resume must ignore the tmp debris (the elastic chaos rung)
        faults.crash_in_checkpoint_write_if_armed(
            f"{path}.tmp.{os.getpid()}", blob)
        atomic_write_bytes(path, blob)
        Log.debug("Checkpoint saved: %s (%d bytes)", path, len(blob))
        self._rotate()
        return path

    def _rotate(self):
        entries = self.checkpoints()
        for _, path in entries[:-self.keep_last_k]:
            try:
                os.unlink(path)
                Log.debug("Checkpoint rotated out: %s", path)
            except OSError as e:
                Log.warning("could not remove old checkpoint %s: %s",
                            path, e)

    def load(self, path):
        """Read + validate one checkpoint file. Raises CheckpointError."""
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError as e:
            raise CheckpointError(f"cannot read {path}: {e}")
        return decode_checkpoint(blob)

    def load_latest(self):
        """(state, path) of the newest VALID checkpoint, or (None, None).
        Invalid files are skipped with a warning, newest first."""
        for iteration, path in reversed(self.checkpoints()):
            try:
                state = self.load(path)
            except CheckpointError as e:
                Log.warning("skipping invalid checkpoint %s: %s", path, e)
                continue
            Log.info("Resuming from checkpoint %s (iteration %d)",
                     path, iteration)
            return state, path
        return None, None
