"""Fault-injection harness for the fault-tolerance subsystem.

No reference equivalent: the reference's recovery story is "rerun the
job". Here preemption-safety is a first-class feature, so each recovery
path (checkpoint/resume, digest validation, non-finite guardrails,
distributed-init retry) carries an injection point this module drives,
and tests/test_fault_tolerance.py proves every path end-to-end.

Activation is env- or API-driven:

- env: ``LIGHTGBM_TPU_FAULTS="crash_at_iteration=5,corrupt_digest=1"``
  (read once per process at import; re-read with `reload_from_env`).
- API: ``faults.set_fault("crash_at_iteration", 5)`` / `clear_faults()`
  (what the test suite uses; `injected_faults` is the context-manager
  form that always restores the previous state).

Known fault names (value semantics in parentheses):

- ``crash_at_iteration`` (iteration index): raise `InjectedFault` —
  or `os._exit(43)` when ``hard_crash`` is also set — just before
  boosting iteration k trains (models/gbdt.py; a fused block containing
  iteration k crashes at its block boundary, the preemption analog).
- ``nan_grad_at_iteration`` (iteration index): poison the gradients of
  iteration k with NaN (models/gbdt.py), exercising the
  `nonfinite_guard` policy.
- ``truncate_checkpoint`` (count): the next k checkpoint saves write
  only the first half of the file's bytes (utils/checkpoint.py).
- ``corrupt_digest`` (count): the next k checkpoint saves flip a
  payload byte after the digest was computed (utils/checkpoint.py).
- ``fail_distributed_init`` (count): the next k attempts of
  `jax.distributed.initialize` fail (parallel/distributed.py).
- ``hard_crash`` (flag): escalate `crash_at_iteration` from a Python
  exception to `os._exit(43)` — a true no-cleanup kill, the closest
  in-process analog of a TPU preemption.

Distributed-supervisor faults (rank-targeted; the value is
``"rank:iteration"``, or a bare iteration to hit every rank). These
fire only on the FIRST launch of a supervised job: the supervisor
(lightgbm_tpu/supervisor.py) stamps LIGHTGBM_TPU_RESTART_ATTEMPT on
relaunches, so a restarted worker trains through — the injection
models one preemption/straggler event, not a permanently broken rank.

- ``rank_crash_at_iteration`` (``rank:iter``): `os._exit(43)` the
  matching rank just before boosting iteration k — a dead peer; the
  survivors' heartbeat monitor must detect it within
  `heartbeat_timeout_s` (parallel/heartbeat.py).
- ``rank_hang_at_iteration`` (``rank:iter``): the matching rank sleeps
  forever just before iteration k — a straggler/hang; the PEERS block
  in the next collective until their watchdog (`collective_timeout_s`)
  fires.
- ``heartbeat_stale`` (rank index; -1 = every rank): the matching
  rank's heartbeat publisher stops writing while training continues —
  models a wedged monitor/filesystem so peers declare it dead.

Elastic out-of-core faults (the preemption surface of the shared
block-store gang, data/ooc_parallel.py + docs/Out-of-Core.md). Like
the rank faults above, the one-shot kills are disarmed on a restarted
attempt — each models one preemption event:

- ``rank_crash_in_prefetch`` (rank index; -1 = every rank):
  `os._exit(43)` the matching rank from INSIDE the block-prefetch
  producer thread, right after its first block read of a pass
  (data/prefetch.py) — a preemption landing while disk/device staging
  is in flight, the window where a naive design would leave a torn
  store. The store is read-only during training, so survivors must
  adopt the dead rank's blocks with zero re-binning.
- ``crash_in_checkpoint_write`` (count): the next k checkpoint saves
  write HALF the payload to the sibling tmp file and `os._exit(43)`
  before the atomic rename (utils/checkpoint.py) — a preemption
  mid-checkpoint-write at a block boundary. The previous snapshot must
  survive (rename never happened) and the resume must ignore the tmp
  debris.
- ``stale_ownership`` (rank index; -1 = every rank): the matching rank
  derives its owned block range from a world ONE LARGER than the real
  one — a stale ownership lease after an elastic re-shard. The gang's
  cross-rank tiling check (parallel/machines.py check_block_tiling)
  must refuse to train rather than drop/double-count blocks.
- ``bitrot_block_on_restart`` (block index): on a RESTARTED attempt
  only, flip one byte of that block's file on disk before the
  post-restart re-verification pass — bit-rot landing between
  attempts. The resuming rank's owned-block crc re-check
  (data/block_store.py BlockStore.reverify) must fail with a named
  BlockStoreError instead of training on garbage.

Serving chaos faults (the resilience layer; serving/server.py,
serving/batcher.py, fleet/registry.py). These are readable through a
per-server overrides dict (`serving_chaos`) so a multi-replica chaos
test can slow ONE in-process replica while its siblings stay healthy —
the env/API global still applies to every replica that has no
override:

- ``slow_replica_ms`` (milliseconds): every predict handler sleeps
  that long before dispatch — a degraded/overcommitted replica.
- ``error_rate`` (integer percent): that share of predict requests
  fail with an injected 500. Firing is DETERMINISTIC (Bresenham over a
  request counter, `error_rate_fires`) so chaos assertions are exact
  and the nondeterminism lint stays clean — no RNG.
- ``drop_connection`` (count): the next k predict replies close the
  socket without writing a response — a torn connection the router
  must retry elsewhere.
- ``wedge_batcher`` (flag): the MicroBatcher worker parks before
  taking work until the fault clears — queue grows, admission control
  must shed; clearing the fault un-wedges without a restart.
- ``corrupt_registry_version`` (count): the next k
  `ModelRegistry.verify` calls raise RegistryError as if the manifest
  checksums failed — a torn publish the follower must refuse to swap.
"""

import os
import time

ENV_VAR = "LIGHTGBM_TPU_FAULTS"

# exit code of a hard_crash kill; tests assert on it
HARD_CRASH_EXIT_CODE = 43


class InjectedFault(RuntimeError):
    """Raised by an armed injection point (soft crash mode)."""


_active = {}


def _parse_spec(spec):
    out = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        name, _, value = item.partition("=")
        name = name.strip()
        if not name:
            continue
        try:
            out[name] = int(value) if value else 1
        except ValueError:
            out[name] = value.strip()
    return out


def reload_from_env():
    """Replace the active fault set with $LIGHTGBM_TPU_FAULTS."""
    _active.clear()
    _active.update(_parse_spec(os.environ.get(ENV_VAR, "")))


def set_fault(name, value=1):
    _active[name] = value


def clear_faults():
    _active.clear()


def active():
    return dict(_active)


def get(name, default=None):
    return _active.get(name, default)


def consume(name):
    """Count-based faults: True (and decrement) while the counter is
    positive; a negative counter fires forever."""
    count = _active.get(name)
    if not isinstance(count, int) or count == 0:
        return False
    if count > 0:
        _active[name] = count - 1
    return True


class injected_faults:
    """Context manager arming a fault set and restoring the previous
    state on exit (the test suite's idiom)."""

    def __init__(self, **faults):
        self._faults = faults
        self._saved = None

    def __enter__(self):
        self._saved = dict(_active)
        _active.update(self._faults)
        return self

    def __exit__(self, *exc):
        _active.clear()
        _active.update(self._saved)
        return False


# ------------------------------------------------------- serving chaos

def serving_chaos(overrides=None):
    """Merged fault view for the serving layer: the process-global
    fault set overlaid with a per-server overrides dict (so one
    in-process replica can be slowed/broken while siblings sharing the
    process-global table stay healthy)."""
    merged = dict(_active)
    if overrides:
        merged.update(overrides)
    return merged


def consume_from(name, overrides=None):
    """Count-based consume honoring a per-server overrides dict first:
    decrements the override counter when the name is overridden there,
    the global counter otherwise. Negative counters fire forever."""
    if overrides is not None and name in overrides:
        count = overrides.get(name)
        if not isinstance(count, int) or count == 0:
            return False
        if count > 0:
            overrides[name] = count - 1
        return True
    return consume(name)


def error_rate_fires(state, rate):
    """Deterministic percent-based firing for ``error_rate``: `rate` is
    an integer percent; request k fires when floor(k*rate/100) advances
    (Bresenham), so EXACTLY rate% of requests fail with no RNG — chaos
    assertions stay exact and reproducible. `state` is a mutable dict
    owned by the caller (one per server)."""
    try:
        rate = int(rate)
    except (TypeError, ValueError):
        return False
    if rate <= 0:
        return False
    rate = min(100, rate)
    state["seen"] = state.get("seen", 0) + 1
    should_have_fired = (state["seen"] * rate) // 100
    if should_have_fired > state.get("fired", 0):
        state["fired"] = should_have_fired
        return True
    return False


# --------------------------------------------------------- rank targeting

_rank = None


def set_rank(rank):
    """Record this process's distributed rank for rank-targeted faults
    (called by parallel/distributed.py init and the supervisor's env)."""
    global _rank
    _rank = int(rank)


def current_rank():
    if _rank is not None:
        return _rank
    try:
        return int(os.environ.get("LIGHTGBM_TPU_RANK", "0"))
    except ValueError:
        return 0


def _rank_iter_spec(name):
    """Parse a rank-targeted iteration fault value: ``"rank:iter"``
    targets one rank, a bare integer targets every rank. Returns
    (rank_or_None, iteration) or None when unarmed/unparsable."""
    value = _active.get(name)
    if value is None:
        return None
    if isinstance(value, int):
        return None, value
    text = str(value)
    rank_s, sep, iter_s = text.partition(":")
    if not sep:
        return None
    try:
        return int(rank_s), int(iter_s)
    except ValueError:
        return None


def _is_restarted_attempt():
    """True inside a supervisor relaunch (attempt > 0): one-shot rank
    faults must not re-fire after the restart they exist to provoke."""
    return os.environ.get("LIGHTGBM_TPU_RESTART_ATTEMPT", "0") not in ("", "0")


# ------------------------------------------------------------ fire points

def crash_if_reached(first_iteration, num_iterations=1):
    """Crash when `crash_at_iteration` falls inside
    [first_iteration, first_iteration + num_iterations). Called at the
    start of every boosting iteration (per-iteration path) and at every
    fused block launch (the whole block is one device program, so a
    preemption mid-block loses the block — crashing at its start models
    exactly that)."""
    k = _active.get("crash_at_iteration")
    if not isinstance(k, int):
        return
    if first_iteration <= k < first_iteration + num_iterations:
        if _active.get("hard_crash"):
            os._exit(HARD_CRASH_EXIT_CODE)
        raise InjectedFault(
            f"injected crash at boosting iteration {k}")


def rank_crash_if_reached(first_iteration, num_iterations=1):
    """`rank_crash_at_iteration`: hard-kill (`os._exit(43)`) the
    matching rank when iteration k falls inside
    [first_iteration, first_iteration + num_iterations). No soft mode:
    a rank death the peers must DETECT has to skip every finally/atexit
    path, exactly like a preemption."""
    spec = _rank_iter_spec("rank_crash_at_iteration")
    if spec is None or _is_restarted_attempt():
        return
    rank, k = spec
    if rank is not None and rank != current_rank():
        return
    if first_iteration <= k < first_iteration + num_iterations:
        os._exit(HARD_CRASH_EXIT_CODE)


def rank_hang_if_reached(first_iteration, num_iterations=1):
    """`rank_hang_at_iteration`: the matching rank sleeps forever just
    before iteration k. Peers entering the next collective then block —
    the scenario the collective watchdog exists to bound. The hung
    process itself keeps heartbeating (it is alive, just stuck), so
    only a watchdog — not the heartbeat monitor — can catch this."""
    spec = _rank_iter_spec("rank_hang_at_iteration")
    if spec is None or _is_restarted_attempt():
        return
    rank, k = spec
    if rank is not None and rank != current_rank():
        return
    if first_iteration <= k < first_iteration + num_iterations:
        from .log import Log
        Log.warning("injected hang at boosting iteration %d (rank %d)",
                    k, current_rank())
        while True:
            time.sleep(3600)


def _rank_flag_fires(name, rank=None):
    """Shared semantics of rank-valued faults: value == rank fires that
    rank, -1 fires every rank. None when unarmed/unparsable."""
    value = _active.get(name)
    if value is None:
        return False
    try:
        value = int(value)
    except (TypeError, ValueError):
        return False
    if rank is None:
        rank = current_rank()
    return value in (-1, int(rank))


def rank_crash_in_prefetch_if_reached():
    """`rank_crash_in_prefetch`: hard-kill the matching rank from the
    prefetch producer thread (data/prefetch.py calls this right after
    a block read lands in the staging ring). `os._exit` from a daemon
    thread takes the whole process down with no cleanup — exactly a
    preemption mid-staging. One-shot: disarmed on a restarted
    attempt."""
    if _is_restarted_attempt():
        return
    if _rank_flag_fires("rank_crash_in_prefetch"):
        os._exit(HARD_CRASH_EXIT_CODE)


def crash_in_checkpoint_write_if_armed(tmp_path, blob):
    """`crash_in_checkpoint_write`: write half of `blob` to `tmp_path`
    and hard-exit — a preemption mid-checkpoint-write. The caller's
    atomic rename never runs, so the previous checkpoint survives and
    the half-written tmp file is the debris a resume must ignore.
    Disarmed on a restarted attempt (one preemption event)."""
    if _is_restarted_attempt():
        return
    if not consume("crash_in_checkpoint_write"):
        return
    with open(tmp_path, "wb") as f:
        f.write(blob[:max(1, len(blob) // 2)])
        f.flush()
        os.fsync(f.fileno())
    os._exit(HARD_CRASH_EXIT_CODE)


def stale_ownership_world(num_shards):
    """`stale_ownership`: the world size the matching rank should use
    when deriving its owned block range — one larger than the real one,
    modelling a lease from before an elastic re-shard. Identity for
    unmatched ranks / unarmed. NOT disarmed on restart: the stale view
    is a property of the lease, not a one-shot event; the tiling check
    must catch it on every attempt it survives."""
    if _rank_flag_fires("stale_ownership"):
        return int(num_shards) + 1
    return int(num_shards)


def bitrot_block_if_armed(block_path_of, lo, hi):
    """`bitrot_block_on_restart`: on a restarted attempt, flip one byte
    of the armed block's file (value = block index) when it falls in
    this rank's owned range [lo, hi). `block_path_of` maps a block
    index to its file path. Consumed once; fires only on restart — the
    rot happened BETWEEN attempts, so the re-verification pass
    (BlockStore.reverify) is the layer that must catch it."""
    if not _is_restarted_attempt():
        return
    target = _active.get("bitrot_block_on_restart")
    if not isinstance(target, int) or not (lo <= target < hi):
        return
    if not consume("bitrot_block_on_restart"):
        return
    path = block_path_of(target)
    with open(path, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        byte = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([byte[0] ^ 0xFF]))


def heartbeat_suppressed(rank=None):
    """`heartbeat_stale`: True when `rank`'s heartbeat publisher must
    skip its writes (value = rank index; -1 suppresses every rank).
    `rank` defaults to this process's rank; the heartbeat service
    passes its own (tests run several ranks in one process)."""
    value = _active.get("heartbeat_stale")
    if value is None:
        return False
    try:
        value = int(value)
    except (TypeError, ValueError):
        return False
    if rank is None:
        rank = current_rank()
    return value in (-1, int(rank))


def poison_gradients_if_armed(iteration, gradients, hessians):
    """When `nan_grad_at_iteration` == iteration, return copies of
    (gradients, hessians) with NaN planted in class 0 (row index
    `nan_grad_row`, default 3, clamped to the array)."""
    k = _active.get("nan_grad_at_iteration")
    if not isinstance(k, int) or k != iteration:
        return gradients, hessians
    import numpy as np
    g = np.array(gradients, dtype=np.float32, copy=True)
    row = min(int(_active.get("nan_grad_row", 3)), g.shape[-1] - 1)
    g.reshape(g.shape[0] if g.ndim > 1 else 1, -1)[0, row] = np.nan
    return g, hessians


def mangle_checkpoint_blob(blob):
    """Apply `truncate_checkpoint` / `corrupt_digest` to the final
    checkpoint file bytes. Returns the (possibly mangled) bytes."""
    if consume("truncate_checkpoint"):
        blob = blob[:max(1, len(blob) // 2)]
    if consume("corrupt_digest"):
        flip = len(blob) - 1  # payload tail: past header and digest
        blob = blob[:flip] + bytes([blob[flip] ^ 0xFF])
    return blob


reload_from_env()
