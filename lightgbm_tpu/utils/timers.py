"""DEPRECATED shim: per-phase timers moved to the telemetry package.

`PhaseTimers` is now `lightgbm_tpu.telemetry.trace.SpanTracer` (a
superset: nesting, tags, delta snapshots, jax.profiler annotation
passthrough) and the training loop keeps a PER-BOOSTER tracer
(`GBDT.tracer`) instead of this module's process-global singleton —
two Boosters trained in one process used to accumulate into the same
`TIMERS.acc`, cross-contaminating every phase total.

The module-level `TIMERS` instance remains for external callers that
imported it (same `.phase()/.add()/.reset()/.snapshot()/.report()`
API), but nothing inside the package writes to it anymore. Migrate to
`booster.gbdt.tracer` (Python API) / `self.boosting.tracer` (CLI
embedders) — see docs/Observability.md.
"""

from ..telemetry.trace import SpanTracer as PhaseTimers  # noqa: F401

# Deprecated process-global instance (see module docstring).
TIMERS = PhaseTimers()
