"""Per-phase wall-clock timers.

Reference observability surface: the cumulative network-time counters in
include/LightGBM/network.h / src/network/linkers.h:195-212 and the
per-iteration / load timers sprinkled through application.cpp. On TPU
the phases that matter are different — gradient computation, tree build
(device program + the scalar stop-check sync), score updates, host<->
device sync, and metric evaluation — so the registry tracks those. XLA
owns collective scheduling inside the compiled program; fine-grained
collective time comes from `jax.profiler` traces (CLI flag `profile=1`),
not host timers.

Usage:
    with TIMERS.phase("build"):
        ...
    Log.debug-level report via TIMERS.report() at end of training.
"""

import time
from collections import defaultdict
from contextlib import contextmanager


class PhaseTimers:
    def __init__(self):
        self.acc = defaultdict(float)
        self.cnt = defaultdict(int)

    @contextmanager
    def phase(self, name):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.acc[name] += time.perf_counter() - t0
            self.cnt[name] += 1

    def add(self, name, seconds):
        self.acc[name] += seconds
        self.cnt[name] += 1

    def reset(self):
        self.acc.clear()
        self.cnt.clear()

    def snapshot(self):
        """{phase: total_seconds} for machine-readable reporting (the
        bench emits this in its result JSON)."""
        return {k: round(v, 3) for k, v in self.acc.items()}

    def report(self):
        """One line per phase, largest first."""
        lines = []
        for name in sorted(self.acc, key=lambda k: -self.acc[k]):
            n = max(self.cnt[name], 1)
            lines.append("%-12s %8.3fs total, %7.2fms/call x%d"
                         % (name, self.acc[name], 1e3 * self.acc[name] / n,
                            self.cnt[name]))
        return "\n".join(lines)


TIMERS = PhaseTimers()
