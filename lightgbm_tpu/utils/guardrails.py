"""Non-finite guardrails: detect NaN/Inf training state early.

No reference equivalent: the reference trains garbage trees silently
when a custom objective emits NaN or a divergent learning rate blows up
the scores. Here a configurable policy (`nonfinite_guard` knob,
config.py) is applied to gradients/hessians before every tree build and
to scores at fused-block boundaries:

- ``raise`` (default): abort with a diagnostic naming the first
  offending (class, row) pair and the offending value;
- ``warn_skip``: log a warning and skip the boosting round (no tree is
  appended for it);
- ``clamp``: sanitize in place — NaN -> 0, +-Inf -> +-CLAMP_MAGNITUDE —
  and log once per offending iteration;
- ``off``: no checks (no host sync on the guarded paths).
"""

import numpy as np

from .log import Log, LightGBMError

POLICIES = ("raise", "warn_skip", "clamp", "off")

# Inf replacement under `clamp`: large enough to dominate any sane
# gradient, small enough that a full histogram's f32 accumulation
# (<= ~2^24 rows per bin) stays finite.
CLAMP_MAGNITUDE = 1e15


def first_nonfinite(arr):
    """(class_idx, row_idx, value) of the first non-finite entry of a
    (num_class, num_data)-shaped array, or None when all finite."""
    a = np.asarray(arr)
    flat = a.reshape(-1)
    bad = ~np.isfinite(flat)
    if not bad.any():
        return None
    idx = int(np.argmax(bad))
    cols = a.shape[-1] if a.ndim > 1 else flat.shape[0]
    return idx // cols, idx % cols, float(flat[idx])


def describe(what, iteration, cls, row, value):
    return (f"Non-finite {what} at iteration {iteration}: class {cls}, "
            f"row {row} is {value!r}. A custom objective returning "
            "NaN/Inf or a divergent learning_rate is the usual cause; "
            "set nonfinite_guard=warn_skip|clamp to train through it, "
            "or nonfinite_guard=off to disable this check.")


def guard_gradients(gradients, hessians, iteration, policy):
    """Apply the policy to a gradient/hessian pair.

    Returns (gradients, hessians, skip): `skip` True means the caller
    must skip this boosting round (warn_skip). Under `clamp` the
    returned arrays are sanitized host copies. Raises LightGBMError
    under `raise`."""
    if policy in ("off", None):
        return gradients, hessians, False
    for what, arr in (("gradient", gradients), ("hessian", hessians)):
        hit = first_nonfinite(arr)
        if hit is None:
            continue
        msg = describe(what, iteration, *hit)
        if policy == "raise":
            raise LightGBMError(msg)
        if policy == "warn_skip":
            Log.warning("%s Skipping this boosting round.", msg)
            return gradients, hessians, True
        # clamp
        Log.warning("%s Clamping (NaN->0, Inf->+-%g).", msg,
                    CLAMP_MAGNITUDE)
        gradients = np.nan_to_num(
            np.asarray(gradients, dtype=np.float32), nan=0.0,
            posinf=CLAMP_MAGNITUDE, neginf=-CLAMP_MAGNITUDE)
        hessians = np.nan_to_num(
            np.asarray(hessians, dtype=np.float32), nan=0.0,
            posinf=CLAMP_MAGNITUDE, neginf=-CLAMP_MAGNITUDE)
    return gradients, hessians, False


def guard_scores(score, iteration, policy, what="model score"):
    """Score guard (fused-block boundaries and per-iteration path).
    Scores cannot be meaningfully clamped mid-training, so every
    non-`off` policy detects; only `raise` aborts."""
    if policy in ("off", None):
        return
    hit = first_nonfinite(score)
    if hit is None:
        return
    msg = describe(what, iteration, *hit)
    if policy == "raise":
        raise LightGBMError(msg)
    Log.warning("%s", msg)


def validate_labels(label, weights=None):
    """Dataset-level guardrail (objective init): non-finite labels or
    weights poison every gradient, so fail fast with the row index."""
    lab = np.asarray(label)
    bad = ~np.isfinite(lab)
    if bad.any():
        row = int(np.argmax(bad))
        Log.fatal("Label contains non-finite value %r at row %d",
                  float(lab.reshape(-1)[row]), row)
    if weights is not None:
        w = np.asarray(weights)
        bad = ~np.isfinite(w)
        if bad.any():
            row = int(np.argmax(bad))
            Log.fatal("Weight contains non-finite value %r at row %d",
                      float(w.reshape(-1)[row]), row)
