"""CLI entry: `python -m lightgbm_tpu task=train config=train.conf ...`
(the reference's `lightgbm` binary, src/main.cpp)."""

# before any jax use: 1-core runners need a second virtual host device
# or embedded host callbacks can deadlock the CPU client (utils/hostenv)
from .utils.hostenv import ensure_callback_worker_devices

ensure_callback_worker_devices()

from .application import main

main()
