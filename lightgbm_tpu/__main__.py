"""CLI entry: `python -m lightgbm_tpu task=train config=train.conf ...`
(the reference's `lightgbm` binary, src/main.cpp)."""

from .application import main

main()
