"""Config system: parameter structs, parsing, alias table.

Reference: include/LightGBM/config.h:20-406, src/io/config.cpp:15-349.
One flat Config object holds every parameter (the reference splits them
into IO/Objective/Metric/Tree/Boosting/Network sub-structs; we keep the
same names and defaults, flat, because the TPU build passes a single
hashable config into jitted tree-build steps).
"""

import os
from dataclasses import dataclass, fields

from .utils.log import Log, check
from .utils.random import Random

# Alias table, reference config.h:316-406 (~70 entries).
PARAMETER_ALIASES = {
    "config": "config_file",
    "nthread": "num_threads",
    "random_seed": "seed",
    "num_thread": "num_threads",
    "boosting": "boosting_type",
    "boost": "boosting_type",
    "application": "objective",
    "app": "objective",
    "train_data": "data",
    "train": "data",
    "model_output": "output_model",
    "model_out": "output_model",
    "model_input": "input_model",
    "model_in": "input_model",
    "predict_result": "output_result",
    "prediction_result": "output_result",
    "valid": "valid_data",
    "test_data": "valid_data",
    "test": "valid_data",
    "is_sparse": "is_enable_sparse",
    "tranining_metric": "is_training_metric",
    "train_metric": "is_training_metric",
    "ndcg_at": "ndcg_eval_at",
    "min_data_per_leaf": "min_data_in_leaf",
    "min_data": "min_data_in_leaf",
    "min_child_samples": "min_data_in_leaf",
    "min_sum_hessian_per_leaf": "min_sum_hessian_in_leaf",
    "min_sum_hessian": "min_sum_hessian_in_leaf",
    "min_hessian": "min_sum_hessian_in_leaf",
    "min_child_weight": "min_sum_hessian_in_leaf",
    "num_leaf": "num_leaves",
    "sub_feature": "feature_fraction",
    "colsample_bytree": "feature_fraction",
    "num_iteration": "num_iterations",
    "num_tree": "num_iterations",
    "num_round": "num_iterations",
    "num_trees": "num_iterations",
    "num_rounds": "num_iterations",
    "sub_row": "bagging_fraction",
    "subsample": "bagging_fraction",
    "subsample_freq": "bagging_freq",
    "shrinkage_rate": "learning_rate",
    "tree": "tree_learner",
    "num_machine": "num_machines",
    "local_port": "local_listen_port",
    "two_round_loading": "use_two_round_loading",
    "two_round": "use_two_round_loading",
    "mlist": "machine_list_file",
    "is_save_binary": "is_save_binary_file",
    "save_binary": "is_save_binary_file",
    "early_stopping_rounds": "early_stopping_round",
    "early_stopping": "early_stopping_round",
    "verbosity": "verbose",
    "header": "has_header",
    "label": "label_column",
    "weight": "weight_column",
    "group": "group_column",
    "query": "group_column",
    "query_column": "group_column",
    "ignore_feature": "ignore_column",
    "blacklist": "ignore_column",
    "categorical_feature": "categorical_column",
    "cat_column": "categorical_column",
    "cat_feature": "categorical_column",
    "predict_raw_score": "is_predict_raw_score",
    "predict_leaf_index": "is_predict_leaf_index",
    "raw_score": "is_predict_raw_score",
    "leaf_index": "is_predict_leaf_index",
    "min_split_gain": "min_gain_to_split",
    "topk": "top_k",
    "reg_alpha": "lambda_l1",
    "reg_lambda": "lambda_l2",
    "num_classes": "num_class",
    "save_period": "snapshot_freq",
    "checkpoint_freq": "snapshot_freq",
    "checkpoint_dir": "snapshot_dir",
    "nan_policy": "nonfinite_guard",
}


def key_alias_transform(params: dict) -> dict:
    """Normalize aliased keys; explicit canonical keys win (config.h:394-404)."""
    out = dict(params)
    for k, v in params.items():
        canon = PARAMETER_ALIASES.get(k)
        if canon is not None:
            out.pop(k, None)
            if canon not in params:
                out[canon] = v
    return out


def str2map(parameters: str) -> dict:
    """Parse 'k1=v1 k2=v2' strings (config.cpp Str2Map)."""
    params = {}
    for arg in parameters.replace("\t", " ").replace("\n", " ").replace("\r", " ").split(" "):
        arg = arg.strip()
        if not arg:
            continue
        kv = arg.split("=")
        if len(kv) == 2:
            key = kv[0].strip().strip('"').strip("'")
            val = kv[1].strip().strip('"').strip("'")
            if key:
                params[key] = val
        else:
            Log.warning("Unknown parameter %s", arg)
    return key_alias_transform(params)


def _parse_bool(value) -> bool:
    if isinstance(value, bool):
        return value
    v = str(value).lower()
    if v in ("false", "-", "0"):
        return False
    if v in ("true", "+", "1"):
        return True
    Log.fatal('Parameter should be "true"/"+" or "false"/"-", got [%s]', value)


@dataclass
class Config:
    """All parameters, reference defaults (config.h:91-226)."""

    # --- overall (config.h:229-244) ---
    task: str = "train"
    seed: int = None  # fans out to sub-seeds when set (config.cpp:40-47)
    num_threads: int = 0
    boosting_type: str = "gbdt"
    objective: str = "regression"
    metric: tuple = ()
    tree_learner: str = "serial"

    # --- IO (config.h:91-133) ---
    max_bin: int = 256
    num_class: int = 1
    data_random_seed: int = 1
    data: str = ""
    valid_data: tuple = ()
    output_model: str = "LightGBM_model.txt"
    output_result: str = "LightGBM_predict_result.txt"
    input_model: str = ""
    verbose: int = 1
    num_iteration_predict: int = -1
    is_pre_partition: bool = False
    is_enable_sparse: bool = True
    # EFB conflict tolerance: fraction of rows a bundle may have in
    # conflict (0.0 = only perfectly-exclusive features share a slot;
    # conflicting cells keep the first member's bin). The reference v0
    # predates EFB — its per-feature sparse bins tolerate any overlap
    # (sparse_bin.hpp); this knob recovers that capacity for
    # NEAR-exclusive wide data.
    max_conflict_rate: float = 0.0
    use_two_round_loading: bool = False
    is_save_binary_file: bool = False
    enable_load_from_binary_file: bool = True
    bin_construct_sample_cnt: int = 50000
    is_predict_leaf_index: bool = False
    is_predict_raw_score: bool = False
    has_header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""
    categorical_column: str = ""

    # --- objective (config.h:136-151) ---
    sigmoid: float = 1.0
    label_gain: tuple = ()
    max_position: int = 20
    is_unbalance: bool = False
    scale_pos_weight: float = 1.0

    # --- metric (config.h:154-162) ---
    ndcg_eval_at: tuple = (1, 2, 3, 4, 5)

    # --- tree (config.h:166-186) ---
    min_data_in_leaf: int = 100
    min_sum_hessian_in_leaf: float = 10.0
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    num_leaves: int = 127
    feature_fraction_seed: int = 2
    feature_fraction: float = 1.0
    histogram_pool_size: float = -1.0
    max_depth: int = -1
    top_k: int = 20
    # piece-wise linear leaves (Shi et al., arXiv:1802.05640; models/
    # linear_leaves.py, docs/Linear-Trees.md): after the split search
    # fixes the structure, fit a ridge model per leaf on the leaf's
    # root->leaf path features (host f64 normal equations, one stacked
    # solve across the frontier). Leaves too small or degenerate fall
    # back to their constant Newton value.
    linear_tree: bool = False
    # ridge regularizer added to the feature diagonal of each leaf's
    # normal matrix (the intercept is not regularized)
    linear_lambda: float = 0.01
    # cap on per-leaf model width: the first N distinct path features
    # in root-first order; must stay <= serving's COEF_PAD (8) so a
    # linear challenger reuses the warmed serving kernels
    linear_max_features: int = 8

    # --- boosting (config.h:195-216) ---
    metric_freq: int = 1
    is_training_metric: bool = False
    num_iterations: int = 10
    learning_rate: float = 0.1
    bagging_fraction: float = 1.0
    bagging_seed: int = 3
    bagging_freq: int = 0
    early_stopping_round: int = 0
    drop_rate: float = 0.01
    drop_seed: int = 4
    # GOSS (post-reference extension, models/goss.py)
    top_rate: float = 0.2
    other_rate: float = 0.1

    # --- network (config.h:219-226) ---
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_file: str = ""
    # jax.distributed.initialize hardening (parallel/distributed.py):
    # retry count and first backoff delay (doubles per retry, capped);
    # the per-attempt timeout is `time_out` seconds
    init_retries: int = 3
    init_backoff_s: float = 1.0
    # --- mesh communication (parallel/mesh.py; the reference's analog
    # is the hand-rolled collective selection in src/network/) ---
    # precision of histogram payloads AT THE COLLECTIVE BOUNDARY only
    # (on-device arithmetic stays f32): "pair" exchanges both Kahan
    # words (the serial==data-parallel bit-parity default), "f32" the
    # collapsed word (half the bytes, deterministic), "bf16" quarter
    # the bytes (lossy; AUC-tolerance territory)
    comm_precision: str = "pair"
    # data-parallel histogram exchange: "auto" = reduce-scatter (each
    # rank reduces + searches only its owned feature block; ~W x fewer
    # wire bytes), "reduce_scatter" forces it, "allgather" restores the
    # full-histogram pair allgather (and is what bundled datasets use)
    hist_exchange: str = "auto"
    # feature-shard groups the reduce-scatter exchange is split into:
    # group g+1's collective can be in flight while group g's split
    # search runs (compute/comms overlap); 1 disables grouping
    comm_groups: int = 2

    # --- distributed supervisor (parallel/heartbeat.py, supervisor.py;
    # no reference equivalent) ---
    # peer declared dead after this many seconds without a heartbeat
    # change (0 = heartbeats off); beats publish every timeout/4
    heartbeat_timeout_s: float = 0.0
    # watchdog around blocking collectives: abort (exit code 117) when a
    # device-sync point blocks longer than this (0 = off). Must exceed
    # the worst-case legitimate sync, including a first-iteration compile
    collective_timeout_s: float = 0.0
    # elastic-restart launcher (`python -m lightgbm_tpu.supervisor`):
    # relaunch after a failure, at most max_restarts times
    restart_on_failure: bool = True
    max_restarts: int = 2

    # --- telemetry (lightgbm_tpu/telemetry/; no reference equivalent) ---
    # master switch for the structured run journal (+ /trainz wiring);
    # span tracing and the metrics registry are always on — in-memory
    # and near-free (docs/Observability.md)
    telemetry: bool = False
    # journal directory (rank-suffixed JSONL files, rank 0 merges); the
    # CLI defaults it to the shared run dir (snapshot_dir, else
    # <output_model>.snapshots) so aborts/restarts/resumes land in the
    # same timeline as training progress
    telemetry_dir: str = ""
    # >0 serves the live GET /trainz endpoint on 127.0.0.1:<port>
    telemetry_port: int = 0
    # wrap tracer spans in jax.profiler.TraceAnnotation so host-side
    # phases line up with XLA device traces (`profile=1` workflow)
    telemetry_jax_annotations: bool = False
    # dump the tracer's recent-span ring into the journal at close (a
    # `spans` record) so `tools/export_trace.py` renders fine-grained
    # per-thread slices next to the journal timeline
    telemetry_trace: bool = False
    # warn at end of run for histogram kernels whose live achieved
    # bytes/s (telemetry/roofline.py) fall below this fraction of the
    # measured STREAM copy peak; 0 = off
    roofline_warn_fraction: float = 0.0
    # serving: requests slower than this emit a structured slow-request
    # log line (the `python -m lightgbm_tpu.serve --slow-request-ms`
    # flag mirrors it); 0 = off
    slow_request_ms: float = 1000.0
    # collective latency/overlap attribution (telemetry/comm_profile.py):
    # one `comm` journal record per iteration/block with per-collective
    # host-visible waits, comm_overlap_pct and the straggler view on
    # /trainz. On by default — it only measures when `telemetry` is on
    # (the timing sink is what arms the guarded sections)
    comm_telemetry: bool = True
    # append one `run_summary` record to this JSONL file at run_end
    # (telemetry/history.py; `tools/sentinel.py` trends over the last K
    # records and verify-perf gates on it); "" = off
    run_history: str = ""
    # distributed request tracing (telemetry/disttrace.py): the
    # deterministic hash(trace_id) fraction of healthy traces kept by
    # the tail sampler; error/504/shed and slow-over-slow_request_ms
    # traces are ALWAYS kept regardless (docs/Observability.md)
    trace_sample_rate: float = 0.01
    # keep ONLY error/slow traces: drops even the hash-sampled healthy
    # fraction (the lowest-overhead setting that still catches every
    # incident trace)
    trace_slow_only: bool = False
    # crash flight recorder: dump the span ring + registry snapshot +
    # journal tail to <telemetry_dir>/blackbox-<rank>.json on watchdog
    # abort (exit 117/118), SIGQUIT and unhandled serving exceptions
    blackbox: bool = True
    # documented default port for the fleet aggregator CLI
    # (`python -m lightgbm_tpu.telemetry.aggregate --port`); multi-rank
    # CLI runs offset `telemetry_port` by rank so every rank of a
    # single-host gang is scrapable (application.py)
    aggregate_port: int = 0

    # --- serving resilience (serving/admission.py, fleet/router.py;
    # no reference equivalent — the reference's only resilience is the
    # socket linker's connect-retry loop) ---
    # deadline budget assumed for predict requests that carry no
    # X-Deadline-Ms header (`--deadline-default-ms` serve flag);
    # 0 = requests without a header are never deadline-shed
    deadline_default_ms: float = 0.0
    # admission control: shed (429 + Retry-After) when estimated queue
    # wait exceeds this fraction of the request's deadline budget;
    # brownout (drift/skew/shadow sampling off) engages at half of it
    # (`--shed-queue-budget` serve flag)
    shed_queue_budget: float = 1.0
    # router circuit breaker: consecutive upstream failures that open a
    # replica's breaker (`fleet route --breaker-failures`)
    breaker_failures: int = 5
    # router hedging: send a second copy of a slow predict to another
    # replica once its latency passes this ring quantile (e.g. 0.99);
    # 0 = hedging off (`fleet route --hedge-quantile`)
    hedge_quantile: float = 0.0
    # router retries: extra upstream attempts allowed per client
    # request, as a fraction (0.1 = 10% retry budget bounds error
    # amplification at 1.1x; `fleet route --retry-budget`)
    retry_budget: float = 0.1

    # --- model-quality observability (telemetry/quality.py,
    # io/profile.py, serving/drift.py; no reference equivalent beyond
    # the feature_importance C API) ---
    # journal one `quality` record per iteration/block: split ledger
    # deltas (splits/gain, top features by gain), leaf-value
    # distribution, importance drift; surfaced on /trainz + Prometheus.
    # Requires `telemetry` for the journal; gauges work without it.
    quality_telemetry: bool = False
    # drift comparisons fold each feature's bins into at most this many
    # contiguous groups before PSI (both the training profile baseline
    # and the serving-side rolling histogram fold identically); <= 0 =
    # native mapper resolution
    profile_bins: int = 10
    # serving drift monitor: fraction of request rows run through the
    # bin mappers for the rolling histograms (the `--drift-sample-rate`
    # serve flag mirrors it); 0 = drift monitoring off. The default is
    # sized so the monitor stays under 1% of the raw predict pipe
    # (serving/drift.py cost model); raise it on low-traffic services
    drift_sample_rate: float = 0.001
    # per-feature PSI at or above this emits a structured drift_warn
    # log line and counts into drift_features_over_warn (0.2 is the
    # conventional "investigate" threshold)
    psi_warn: float = 0.2
    # serving skew monitor: fraction of request rows shadow-scored
    # through the host f64 reference path (`--skew-sample-rate`);
    # 0 = skew monitoring off. One diverging row already warns, so a
    # trickle suffices to catch systematic skew
    skew_sample_rate: float = 0.0001
    # structured skew_warn once the diverging-row count reaches this
    # (the serving path is bit-exact vs the reference, so the first
    # skewed row is already a bug); 0 = never warn
    skew_warn: int = 1

    # --- fault tolerance (utils/checkpoint.py; no reference equivalent) ---
    snapshot_freq: int = 0     # checkpoint every k iterations (0 = off)
    snapshot_dir: str = ""     # default: <output_model>.snapshots
    snapshot_keep: int = 3     # rotation: keep the newest k checkpoints
    snapshot_resume: bool = True  # CLI auto-resume from newest valid one
    # NaN/Inf policy for gradients/hessians/scores
    # (utils/guardrails.py): raise | warn_skip | clamp | off
    nonfinite_guard: str = "raise"
    # CSV/TSV ingestion: quarantine up to this many malformed rows
    # (io/parser.py) instead of failing on the first one; 0 = strict
    max_bad_rows: int = 0

    # --- prediction routing (models/gbdt.py predict_raw; no reference
    # equivalent — the reference predicts per-row under OpenMP) ---
    # rows x trees at or above this run the jitted device traversal
    # instead of the host loop ("auto" routing)
    device_predict_cells: int = 20_000_000
    # host-path (rows x trees) cells per traversal block (peak memory)
    host_traverse_cells: int = 4_000_000
    # "auto" = cells-threshold routing; "true" forces the device path,
    # "false" forces the host path. The LIGHTGBM_TPU_DEVICE_PREDICT env
    # flag overrides this knob when set (docs/Parameters.md)
    device_predict: str = "auto"
    # task=predict streams the input file in chunks of this many rows
    # (application.py predict_file) so serving-scale scoring files never
    # materialize as one matrix
    predict_chunk_rows: int = 65536

    # --- out-of-core block-store training (lightgbm_tpu/data/; no
    # reference equivalent — the reference caps datasets at host RAM).
    # out_of_core=true bins the TRAIN dataset once into an on-disk
    # packed-bin block store and trains by streaming blocks through a
    # double-buffered async prefetcher (docs/Out-of-Core.md); trees are
    # bit-identical to in-RAM training with the masked histogram engine
    # (hist_compaction=false) on the same binning
    out_of_core: bool = False
    # rows per on-disk block; rounded up to a multiple of the histogram
    # scan chunk (device_row_chunk) so block boundaries align with the
    # Kahan chunk grid — the alignment the bitwise-parity contract
    # rests on
    block_rows: int = 262144
    # decoded blocks kept resident in an LRU cache on top of the
    # staging ring (0 = staging buffers only)
    block_cache_blocks: int = 0
    # staging buffers the background reader may fill ahead of the
    # consumer; resident bin memory is bounded at (2*prefetch_depth + 1)
    # blocks (staging ring + detached staged blocks in the queue + the
    # one the consumer holds) plus the cache
    prefetch_depth: int = 2
    # block-store directory; default: "<data>.blocks" next to the data
    # file, a fresh temp dir for in-memory matrices
    ooc_dir: str = ""
    # verify each block's manifest digest on its first read
    ooc_verify: bool = True
    # gang training over one shared store: seconds non-zero ranks wait
    # for rank 0's build to publish a signature-matching manifest
    # before giving up (data/block_store.py load_block_store_gang)
    ooc_build_wait_s: float = 600.0

    # derived from tree_learner/num_machines in check_param_conflict,
    # not user knobs — exempt from the Parameters.md row requirement
    is_parallel: bool = False  # graftlint: disable=config-doc-drift
    is_parallel_find_bin: bool = False  # graftlint: disable=config-doc-drift

    # TPU-specific knobs (no reference equivalent)
    device_row_chunk: int = 16384  # rows per histogram-matmul chunk
    # leaf-contiguous builder (models/partitioned.py): "auto" = on for
    # the serial learner on TPU; "true"/"false" force it
    partitioned_build: str = "auto"
    # gather-compacted smaller-child histograms on the dense (masked)
    # builder (ops/histogram.py compacted_histograms): "auto" = on
    # whenever the masked builder runs; "false" restores the full-scan
    # O(N)-per-split path
    hist_compaction: str = "auto"
    # histogram kernel formulation (ops/histogram.py): "auto" = the
    # Pallas streaming kernels on TPU, the f64 np.bincount host
    # callback on CPU, the one-hot einsum elsewhere;
    # "pallas"/"einsum"/"segment"/"bincount" force one formulation
    # (einsum/segment/bincount on TPU disable the Pallas kernels — the
    # supported escape hatch). Resolved once per learner init; the
    # LIGHTGBM_TPU_HIST_MODE env var seeds the process default.
    hist_mode: str = "auto"
    # multi-leaf frontier histogram batching (ops/histogram.py
    # frontier_histograms): "auto"/"true" = the root/bagging re-init
    # pass and the cache-less builder's both-children pass run the
    # one-pass multi-leaf primitive; "false" = per-leaf passes only
    hist_frontier: str = "auto"
    # canonicalize padded row counts to a 3-bit-mantissa grid
    # (ops/ordered_hist.py canonical_row_chunks) so nearby dataset sizes
    # share lowered executables through the persistent compile cache
    shape_bucketing: str = "auto"
    # persistent XLA compilation cache: "auto" = LIGHTGBM_TPU_CACHE_DIR
    # or ~/.cache/lightgbm_tpu/jax_cache, "off" disables, any other
    # value is the cache directory (setup_compilation_cache below)
    compile_cache: str = "auto"
    profile: str = ""              # jax.profiler trace dir ("1" = default dir)

    @classmethod
    def from_params(cls, params) -> "Config":
        """Build a Config from a dict or 'k=v ...' string, applying aliases,
        seed fan-out and conflict checks."""
        if isinstance(params, str):
            params = str2map(params)
        else:
            params = key_alias_transform({k: v for k, v in params.items() if v is not None})
        cfg = cls()
        type_map = {f.name: f.type for f in fields(cls)}
        for key, value in params.items():
            if key in ("config_file", "data", "valid_data", "metric", "label_gain",
                       "ndcg_eval_at", "task", "objective", "boosting_type",
                       "tree_learner", "seed"):
                continue  # handled specially below
            if key not in type_map:
                Log.warning("Unknown parameter: %s", key)
                continue
            cur = getattr(cfg, key)
            if isinstance(cur, bool):
                setattr(cfg, key, _parse_bool(value))
            elif isinstance(cur, int) or cur is None and key != "seed":
                setattr(cfg, key, int(float(value)))
            elif isinstance(cur, float):
                setattr(cfg, key, float(value))
            else:
                setattr(cfg, key, value)

        # seed fan-out (config.cpp:40-47)
        if "seed" in params:
            cfg.seed = int(params["seed"])
            rand = Random(cfg.seed)
            int_max = 2**31 - 1
            cfg.data_random_seed = rand.next_int(0, int_max)
            cfg.bagging_seed = rand.next_int(0, int_max)
            cfg.drop_seed = rand.next_int(0, int_max)
            cfg.feature_fraction_seed = rand.next_int(0, int_max)

        # enum-ish fields
        if "task" in params:
            t = str(params["task"]).lower()
            if t in ("train", "training"):
                cfg.task = "train"
            elif t in ("predict", "prediction", "test"):
                cfg.task = "predict"
            elif t == "refit":
                cfg.task = "refit"
            else:
                Log.fatal("Unknown task type %s", t)
        if "boosting_type" in params:
            b = str(params["boosting_type"]).lower()
            if b in ("gbdt", "gbrt"):
                cfg.boosting_type = "gbdt"
            elif b in ("dart", "goss"):
                cfg.boosting_type = b
            else:
                Log.fatal("Unknown boosting type %s", b)
        if "objective" in params:
            cfg.objective = str(params["objective"]).lower()
        if "tree_learner" in params:
            v = str(params["tree_learner"]).lower()
            mapping = {"serial": "serial",
                       "feature": "feature", "feature_parallel": "feature",
                       "data": "data", "data_parallel": "data",
                       "voting": "voting", "voting_parallel": "voting"}
            if v not in mapping:
                Log.fatal("Unknown tree learner type %s", v)
            cfg.tree_learner = mapping[v]
        if "metric" in params:
            raw = params["metric"]
            if isinstance(raw, str):
                raw = raw.lower().split(",")
            seen, mts = set(), []
            for m in raw:
                m = str(m).strip().lower()
                if m and m not in seen:
                    seen.add(m)
                    mts.append(m)
            cfg.metric = tuple(mts)
        if "data" in params:
            cfg.data = str(params["data"])
        if "valid_data" in params:
            raw = params["valid_data"]
            cfg.valid_data = tuple(raw.split(",")) if isinstance(raw, str) else tuple(raw)
        if "label_gain" in params:
            raw = params["label_gain"]
            cfg.label_gain = tuple(float(x) for x in
                                   (raw.split(",") if isinstance(raw, str) else raw))
        if "ndcg_eval_at" in params:
            raw = params["ndcg_eval_at"]
            ats = sorted(int(x) for x in (raw.split(",") if isinstance(raw, str) else raw))
            check(all(a > 0 for a in ats), "ndcg_eval_at must be positive")
            cfg.ndcg_eval_at = tuple(ats)

        if not cfg.label_gain:
            # label_gain = 2^i - 1 (config.cpp:237-243)
            cfg.label_gain = tuple([0.0] + [float((1 << i) - 1) for i in range(1, 31)])

        cfg.validate()
        cfg.check_param_conflict()
        Log.set_level_from_verbosity(cfg.verbose)
        return cfg

    def validate(self):
        """CHECKs from config.cpp:275-330."""
        check(self.max_bin > 0, "max_bin should be > 0")
        check(self.min_sum_hessian_in_leaf > 1.0 or self.min_data_in_leaf > 0,
              "need min_sum_hessian_in_leaf > 1.0 or min_data_in_leaf > 0")
        check(self.lambda_l1 >= 0.0, "lambda_l1 should be >= 0")
        check(self.lambda_l2 >= 0.0, "lambda_l2 should be >= 0")
        check(self.min_gain_to_split >= 0.0, "min_gain_to_split should be >= 0")
        check(self.num_leaves > 1, "num_leaves should be > 1")
        check(0.0 < self.feature_fraction <= 1.0, "feature_fraction in (0, 1]")
        check(self.max_depth > 1 or self.max_depth < 0, "max_depth should be > 1 or < 0")
        check(self.num_iterations >= 0, "num_iterations should be >= 0")
        check(self.bagging_freq >= 0, "bagging_freq should be >= 0")
        check(0.0 < self.bagging_fraction <= 1.0, "bagging_fraction in (0, 1]")
        check(self.learning_rate > 0.0, "learning_rate should be > 0")
        check(self.early_stopping_round >= 0, "early_stopping_round should be >= 0")
        check(0.0 <= self.drop_rate <= 1.0, "drop_rate in [0, 1]")
        check(self.num_machines >= 1, "num_machines should be >= 1")
        check(self.linear_lambda >= 0.0, "linear_lambda should be >= 0")
        check(self.linear_max_features >= 1,
              "linear_max_features should be >= 1")
        check(0.0 <= self.max_conflict_rate < 1.0,
              "max_conflict_rate in [0, 1)")
        check(self.num_class >= 1, "num_class should be >= 1")
        check(self.max_position > 0, "max_position should be > 0")
        check(self.snapshot_freq >= 0, "snapshot_freq should be >= 0")
        check(self.snapshot_keep >= 1, "snapshot_keep should be >= 1")
        check(self.init_retries >= 0, "init_retries should be >= 0")
        check(str(self.comm_precision).lower() in ("pair", "f32", "bf16"),
              "comm_precision must be pair|f32|bf16")
        check(str(self.hist_exchange).lower() in
              ("auto", "reduce_scatter", "allgather"),
              "hist_exchange must be auto|reduce_scatter|allgather")
        check(self.comm_groups >= 1, "comm_groups should be >= 1")
        check(self.heartbeat_timeout_s >= 0,
              "heartbeat_timeout_s should be >= 0")
        check(self.collective_timeout_s >= 0,
              "collective_timeout_s should be >= 0")
        check(self.max_restarts >= 0, "max_restarts should be >= 0")
        check(self.telemetry_port >= 0, "telemetry_port should be >= 0")
        check(self.aggregate_port >= 0, "aggregate_port should be >= 0")
        check(0.0 <= self.roofline_warn_fraction <= 1.0,
              "roofline_warn_fraction in [0, 1]")
        check(self.slow_request_ms >= 0,
              "slow_request_ms should be >= 0")
        check(self.deadline_default_ms >= 0,
              "deadline_default_ms should be >= 0")
        check(self.shed_queue_budget > 0,
              "shed_queue_budget should be > 0")
        check(self.breaker_failures >= 1,
              "breaker_failures should be >= 1")
        check(0.0 <= self.hedge_quantile < 1.0,
              "hedge_quantile in [0, 1)")
        check(self.retry_budget >= 0,
              "retry_budget should be >= 0")
        check(0.0 <= self.drift_sample_rate <= 1.0,
              "drift_sample_rate in [0, 1]")
        check(0.0 <= self.skew_sample_rate <= 1.0,
              "skew_sample_rate in [0, 1]")
        check(self.psi_warn >= 0.0, "psi_warn should be >= 0")
        check(self.skew_warn >= 0, "skew_warn should be >= 0")
        check(self.max_bad_rows >= 0, "max_bad_rows should be >= 0")
        check(self.device_predict_cells > 0,
              "device_predict_cells should be > 0")
        check(self.host_traverse_cells > 0,
              "host_traverse_cells should be > 0")
        check(str(self.device_predict).lower() in ("auto", "true", "false"),
              "device_predict must be auto|true|false")
        check(self.predict_chunk_rows > 0,
              "predict_chunk_rows should be > 0")
        check(self.block_rows > 0, "block_rows should be > 0")
        check(self.block_cache_blocks >= 0,
              "block_cache_blocks should be >= 0")
        check(self.prefetch_depth >= 1, "prefetch_depth should be >= 1")
        check(str(self.hist_mode).lower() in
              ("auto", "pallas", "einsum", "segment", "bincount"),
              "hist_mode must be auto|pallas|einsum|segment|bincount")
        from .utils.guardrails import POLICIES
        check(self.nonfinite_guard in POLICIES,
              "nonfinite_guard must be one of " + "|".join(POLICIES))

    def check_param_conflict(self):
        """Reference config.cpp:139-187."""
        is_multiclass = self.objective == "multiclass"
        if is_multiclass:
            if self.num_class <= 1:
                Log.fatal("Number of classes should be specified and greater than 1 for multiclass training")
        elif self.task == "train" and self.num_class != 1:
            Log.fatal("Number of classes must be 1 for non-multiclass training")
        for mt in self.metric:
            mt_multiclass = mt in ("multi_logloss", "multi_error")
            if is_multiclass != mt_multiclass:
                Log.fatal("Objective and metrics don't match")

        if self.num_machines > 1:
            self.is_parallel = True
        else:
            self.is_parallel = False
            self.tree_learner = "serial"
        if self.tree_learner == "serial":
            self.is_parallel = False
            self.num_machines = 1
        if self.linear_tree and (self.num_machines > 1
                                 or self.tree_learner != "serial"):
            # the leaf refit accumulates normal equations over the FULL
            # row range on one host; meshed/gang learners would need a
            # cross-rank reduction of the per-leaf (k+1)^2 matrices
            Log.fatal("linear_tree=true is single-process "
                      "(tree_learner=serial, num_machines=1); got "
                      "tree_learner=%s num_machines=%d"
                      % (self.tree_learner, self.num_machines))
        if self.tree_learner in ("serial", "feature"):
            self.is_parallel_find_bin = False
        elif self.tree_learner == "data":
            self.is_parallel_find_bin = True
            if self.histogram_pool_size >= 0:
                Log.warning("Histogram LRU queue was enabled (histogram_pool_size=%f). "
                            "Will disable this to reduce communication costs", self.histogram_pool_size)
                self.histogram_pool_size = -1


# --------------------------------------------------------------------------
# Persistent compilation cache.
#
# The jitted tree builders are a single large XLA program per (shapes,
# config) pair; a cold compile costs 10-60s — more than a whole scaled
# CPU training run. Pointing jax at an on-disk cache makes that a
# once-per-machine cost: every later process with the same lowered
# program (shape bucketing in ops/ordered_hist.py canonical_row_chunks
# widens "same") loads the executable in milliseconds.

_CACHE_HITS = {"hits": 0, "misses": 0, "listener": False}


def _cache_event_listener(name, **kwargs):
    if name == "/jax/compilation_cache/cache_hits":
        _CACHE_HITS["hits"] += 1
    elif name == "/jax/compilation_cache/cache_misses":
        _CACHE_HITS["misses"] += 1


def compile_cache_hits():
    """Process-wide persistent-cache hit count (bench.py reports the
    delta around its warm-up compile as `compile_cache_hit`)."""
    return _CACHE_HITS["hits"]


def setup_compilation_cache(config=None):
    """Configure jax's persistent compilation cache once per process.

    Resolution order: an embedder's existing jax_compilation_cache_dir
    wins (tests / bench children set their own); else
    `config.compile_cache` ("off" disables, a path is used verbatim,
    "auto"/"on" fall through to $LIGHTGBM_TPU_CACHE_DIR or
    ~/.cache/lightgbm_tpu/jax_cache). Returns the active cache dir or
    None. Never fatal: an unwritable directory only costs the cache.
    """
    # the compile ledger rides the same monitoring stream; installing
    # it here covers every compile path (training learners AND the
    # serving warmup both pass through this function)
    from .telemetry.ledger import LEDGER
    LEDGER.install()
    mode = str(getattr(config, "compile_cache", "auto") or "auto")
    if mode.lower() in ("off", "false", "0", "-", "none"):
        return None
    import jax
    if not _CACHE_HITS["listener"]:
        _CACHE_HITS["listener"] = True
        jax.monitoring.register_event_listener(_cache_event_listener)
    existing = jax.config.jax_compilation_cache_dir
    if existing:
        return existing
    if mode.lower() in ("auto", "on", "true", "1", "+"):
        path = (os.environ.get("LIGHTGBM_TPU_CACHE_DIR")
                or os.path.join(os.path.expanduser("~"), ".cache",
                                "lightgbm_tpu", "jax_cache"))
    else:
        path = mode
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # the tree builders' XLA-backend compile can land under the 1s
        # default threshold even when the full trace+lower+compile is
        # 10s+ — cache every executable, the disk cost is a few MB
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        # the cache backend freezes on the process's FIRST compile
        # (dataset construction usually compiles before training config
        # exists); re-initialize it against the directory just set
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.reset_cache()
    except OSError as e:
        Log.warning("compile cache disabled (cannot use %s: %s)", path, e)
        return None
    except Exception as e:  # cache API drift must never break training
        Log.warning("compile cache reset failed (%s); continuing", e)
    return path


def load_config_file(path: str) -> dict:
    """Parse a `key = value` config file (application.cpp:62-98; '#' comments)."""
    params = {}
    with open(path, "r") as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            kv = line.split("=", 1)
            if len(kv) == 2:
                params[kv[0].strip()] = kv[1].strip()
    return key_alias_transform(params)
