"""`python -m lightgbm_tpu.serve model.txt --port 8099`: the serving
CLI (serving/server.py; docs/Serving.md)."""

import sys

from .serving.server import main

if __name__ == "__main__":
    sys.exit(main())
