from .histogram import build_histograms
from .split import find_best_split, leaf_split_gain, leaf_output

__all__ = ["build_histograms", "find_best_split", "leaf_split_gain", "leaf_output"]
