"""Pallas TPU kernels for masked gradient histograms — the hot op.

Reference semantics: the per-feature accumulation loops in
src/io/dense_bin.hpp:16-195 / ordered_sparse_bin.hpp ConstructHistogram:
for every row in one leaf, hist[feature, bin] += (grad, hess, count).

TPU-first design. The reference (and our first build) materializes the
leaf's rows via a maintained row partition and gathers them; on TPU
random gathers are latency-bound and the XLA one-hot einsum materializes
a (F, C, B) one-hot in HBM. This kernel instead streams the FULL bin
matrix once per histogram and selects the leaf with a mask on the
row->leaf map:

    hist[f, b, k] = sum_c [bins[f, c] == b] * [row_leaf[c] == leaf] * ghc[k, c]

Per grid step (a row chunk C): bins (F, C) at their NATURAL packed
width (uint8 for <= 256 bins, int16 above — the DMA moves 1-2 bytes
per cell, never a widened int32), ghc (C, 3) f32 and row_leaf (1, C)
int32 are DMA'd to VMEM (~(F+13)*C bytes at uint8 — the one-hot never
touches HBM). The one-hot is built as (B_pad, C): broadcasting the
lane-resident bins row along SUBLANES is layout-native on the VPU (the
(C, B) orientation would relayout lanes->sublanes per feature, measured
1.4x slower), and the (B_pad, C) @ (C, 3) dot is the natural MXU form.
HBM traffic per histogram is bins + ghc + row_leaf (~44 MB at 1M rows
uint8), two orders of magnitude below the einsum path; the kernel is
VPU-compare-bound, not bandwidth- or MXU-bound.

The FRONTIER variant (frontier_histograms_tpu) carries a static vector
of L leaf ids and a leaf-indexed (L, F, B_pad, 3) accumulator: the bin
matrix streams ONCE for all L histograms (the multi-leaf primitive of
docs/Histogram-Engine.md; compare cost grows with L, HBM traffic does
not). VMEM bounds keep L small — the builder uses L = 2 (both children
of a split) and L = 1 (root/bagging re-init).

f32 operands give true f32 accumulation (better than XLA's default
bfloat16 matmul passes); the count column comes out exactly integral.

Dispatch: masked_histograms/frontier select the Pallas path via
ops/histogram.py use_pallas() — TPU backend with hist_mode auto/pallas
(config knob or LIGHTGBM_TPU_HIST_MODE). hist_mode=einsum/segment/
bincount (or the legacy LIGHTGBM_TPU_DISABLE_PALLAS=1) forces the XLA
fallback on TPU — the escape hatch for kernel regressions; bench.py
uses it as a fallback rung.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# rows per grid step: the transient one-hot is (B_pad, CHUNK) f32 in
# VMEM (4 MB at 256 x 4096); row padding must be a multiple of this.
HIST_CHUNK = 4096

# VMEM budget for a frontier kernel's (L, F, B_pad, 3) f32 accumulator;
# larger frontiers fall back to per-leaf kernel calls.
FRONTIER_VMEM_BYTES = 6 * 1024 * 1024


def _hist_kernel(leaf_ref, bins_ref, ghc_ref, rl_ref, out_ref, *, f, b_pad):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    c = bins_ref.shape[1]
    mask = (rl_ref[0, :] == leaf_ref[0]).astype(jnp.float32)      # (C,) lanes
    ghc_m = ghc_ref[...] * mask[:, None]                          # (C, 3)
    b_iota = jax.lax.broadcasted_iota(jnp.int32, (b_pad, c), 0)
    for i in range(f):
        onehot = (bins_ref[i, :].astype(jnp.int32)[None, :]
                  == b_iota).astype(jnp.float32)                  # (B_pad, C)
        out_ref[i, :, :] += jax.lax.dot_general(
            onehot, ghc_m, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                   # (B_pad, 3)


def _frontier_kernel(leaves_ref, bins_ref, ghc_ref, rl_ref, out_ref,
                     *, l, f, b_pad):
    """Leaf-indexed accumulator: one streamed chunk feeds ALL l leaves'
    histograms. Per chunk: l mask builds + l*f one-hot dots — compare
    cost scales with l, HBM traffic does not."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    c = bins_ref.shape[1]
    b_iota = jax.lax.broadcasted_iota(jnp.int32, (b_pad, c), 0)
    for li in range(l):
        mask = (rl_ref[0, :] == leaves_ref[li]).astype(jnp.float32)
        ghc_m = ghc_ref[...] * mask[:, None]                      # (C, 3)
        for i in range(f):
            onehot = (bins_ref[i, :].astype(jnp.int32)[None, :]
                      == b_iota).astype(jnp.float32)              # (B_pad, C)
            out_ref[li, i, :, :] += jax.lax.dot_general(
                onehot, ghc_m, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)               # (B_pad, 3)


def _bin_pad(num_bins_total):
    return max(((num_bins_total + 127) // 128) * 128, 128)


def masked_histograms_tpu(bins, ghc_t, row_leaf, leaf_id, num_bins_total,
                          interpret=False):
    """hist[f, b, k] over rows with row_leaf == leaf_id (TPU kernel).

    Args:
      bins: (F, N) uint8/int16/int32 bin matrix, N % HIST_CHUNK == 0
        (streamed at its stored width).
      ghc_t: (3, N) float32 stats (grad*inbag, hess*inbag, inbag).
      row_leaf: (N,) int32 row->leaf map.
      leaf_id: int32 scalar (traced ok).
      num_bins_total: static B.

    Returns (F, B, 3) float32.
    """
    f, n = bins.shape
    if n % HIST_CHUNK != 0:
        raise ValueError(f"N={n} must be a multiple of {HIST_CHUNK}")
    b_pad = _bin_pad(num_bins_total)
    grid = (n // HIST_CHUNK,)

    kernel = functools.partial(_hist_kernel, f=f, b_pad=b_pad)
    out = pl.pallas_call(
        kernel,
        interpret=interpret,  # CPU kernel-semantics tests
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # leaf id (1,)
            pl.BlockSpec((f, HIST_CHUNK), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((HIST_CHUNK, 3), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, HIST_CHUNK), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((f, b_pad, 3), lambda i: (0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((f, b_pad, 3), jnp.float32),
    )(jnp.asarray([leaf_id], dtype=jnp.int32), bins, ghc_t.T,
      row_leaf.reshape(1, n))
    hist = out[:, :num_bins_total, :]
    # plain f32 VMEM accumulation: the compensation slot is zero (the
    # f32-vs-f64 parity guard in tests/test_hist_precision.py bounds the
    # resulting error; TPU f64 emulation would forfeit the MXU)
    return hist, jnp.zeros_like(hist)


def frontier_histograms_tpu(bins, ghc_t, row_leaf, leaf_ids, num_bins_total,
                            interpret=False):
    """Multi-leaf kernel: (L, F, B, 3) over rows of each leaf in
    `leaf_ids` (static length L, distinct ids) in ONE stream of the bin
    matrix. Values are bitwise what L masked_histograms_tpu calls
    produce (independent accumulators, same chunk order). Frontiers
    whose accumulator exceeds FRONTIER_VMEM_BYTES fall back to per-leaf
    kernel calls (still one stream per leaf)."""
    l = leaf_ids.shape[0]
    f, n = bins.shape
    if n % HIST_CHUNK != 0:
        raise ValueError(f"N={n} must be a multiple of {HIST_CHUNK}")
    b_pad = _bin_pad(num_bins_total)
    if l * f * b_pad * 3 * 4 > FRONTIER_VMEM_BYTES:
        pairs = [masked_histograms_tpu(bins, ghc_t, row_leaf, leaf_ids[i],
                                       num_bins_total, interpret=interpret)
                 for i in range(l)]
        return (jnp.stack([p[0] for p in pairs]),
                jnp.stack([p[1] for p in pairs]))
    grid = (n // HIST_CHUNK,)

    kernel = functools.partial(_frontier_kernel, l=l, f=f, b_pad=b_pad)
    out = pl.pallas_call(
        kernel,
        interpret=interpret,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # leaf ids (L,)
            pl.BlockSpec((f, HIST_CHUNK), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((HIST_CHUNK, 3), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, HIST_CHUNK), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((l, f, b_pad, 3), lambda i: (0, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((l, f, b_pad, 3), jnp.float32),
    )(leaf_ids.astype(jnp.int32), bins, ghc_t.T, row_leaf.reshape(1, n))
    hist = out[:, :, :num_bins_total, :]
    return hist, jnp.zeros_like(hist)


def masked_histograms_xla(bins, ghc_t, row_leaf, leaf_id, num_bins_total,
                          row_chunk=HIST_CHUNK):
    """Reference XLA implementation (CPU tests / non-TPU backends): the
    chunked histogram kernel of ops/histogram.py (bincount callback on
    CPU, one-hot einsum elsewhere — chunk_mode) with the leaf mask
    folded into the stats. Returns a compensated (value, residual)
    pair."""
    from .histogram import build_histograms_pair
    mask = (row_leaf == leaf_id).astype(jnp.float32)
    ghc = (ghc_t * mask[None, :]).T
    return build_histograms_pair(bins, ghc, num_bins_total, row_chunk)


def masked_histograms(bins, ghc_t, row_leaf, leaf_id, num_bins_total,
                      row_chunk=HIST_CHUNK):
    """Backend dispatch, resolved at trace time. Returns (hist, residual):
    collapse with `hist + residual`, or exchange the pair across shards
    in a fixed order first (parallel/mesh.py pair_allreduce /
    pair_reduce_scatter).

    hist_mode=einsum/segment/bincount (or LIGHTGBM_TPU_DISABLE_PALLAS=1)
    forces the XLA path on TPU (escape hatch for kernel regressions;
    bench.py uses it as a fallback)."""
    from .histogram import use_pallas
    if use_pallas():
        return masked_histograms_tpu(bins, ghc_t, row_leaf, leaf_id,
                                     num_bins_total)
    return masked_histograms_xla(bins, ghc_t, row_leaf, leaf_id,
                                 num_bins_total, row_chunk)
