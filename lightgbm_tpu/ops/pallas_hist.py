"""Pallas TPU kernel for masked gradient histograms — the hot op.

Reference semantics: the per-feature accumulation loops in
src/io/dense_bin.hpp:16-195 / ordered_sparse_bin.hpp ConstructHistogram:
for every row in one leaf, hist[feature, bin] += (grad, hess, count).

TPU-first design. The reference (and our first build) materializes the
leaf's rows via a maintained row partition and gathers them; on TPU
random gathers are latency-bound and the XLA one-hot einsum materializes
a (F, C, B) one-hot in HBM. This kernel instead streams the FULL bin
matrix once per histogram and selects the leaf with a mask on the
row->leaf map:

    hist[f, b, k] = sum_c [bins[f, c] == b] * [row_leaf[c] == leaf] * ghc[k, c]

Per grid step (a row chunk C): bins (F, C) uint8, ghc (3, C) f32 and
row_leaf (1, C) int32 are DMA'd to VMEM (~(F+13)*C bytes — the one-hot
never touches HBM), the mask multiplies ghc, and each feature does one
(3, C) @ (C, B) MXU contraction accumulated into a VMEM-resident
(F, 3, B) output. HBM traffic per histogram is bins + ghc + row_leaf
(~44 MB at 1M rows), two orders of magnitude below the einsum path.

f32 operands give true f32 accumulation (better than XLA's default
bfloat16 matmul passes); the count column comes out exactly integral.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# rows per grid step: the transient one-hot is (CHUNK, B_pad) f32 in
# VMEM (2 MB at 2048 x 256); row padding must be a multiple of this.
HIST_CHUNK = 2048


def _hist_kernel(leaf_ref, bins_ref, ghc_ref, rl_ref, out_ref, *, f, b_pad):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    mask = (rl_ref[0, :] == leaf_ref[0]).astype(jnp.float32)      # (C,)
    ghc_m = ghc_ref[...] * mask[None, :]                          # (3, C)
    c = bins_ref.shape[1]
    col_ids = jax.lax.broadcasted_iota(jnp.int32, (c, b_pad), 1)
    for i in range(f):
        onehot = (bins_ref[i, :].astype(jnp.int32)[:, None]
                  == col_ids).astype(jnp.float32)                 # (C, B_pad)
        out_ref[i, :, :] += jax.lax.dot_general(
            ghc_m, onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


def masked_histograms_tpu(bins, ghc_t, row_leaf, leaf_id, num_bins_total):
    """hist[f, b, k] over rows with row_leaf == leaf_id (TPU kernel).

    Args:
      bins: (F, N) uint8/uint16/int32 bin matrix, N % HIST_CHUNK == 0.
      ghc_t: (3, N) float32 stats (grad*inbag, hess*inbag, inbag).
      row_leaf: (N,) int32 row->leaf map.
      leaf_id: int32 scalar (traced ok).
      num_bins_total: static B.

    Returns (F, B, 3) float32.
    """
    f, n = bins.shape
    if n % HIST_CHUNK != 0:
        raise ValueError(f"N={n} must be a multiple of {HIST_CHUNK}")
    b_pad = max(((num_bins_total + 127) // 128) * 128, 128)
    grid = (n // HIST_CHUNK,)

    kernel = functools.partial(_hist_kernel, f=f, b_pad=b_pad)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # leaf id (1,)
            pl.BlockSpec((f, HIST_CHUNK), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, HIST_CHUNK), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, HIST_CHUNK), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((f, 3, b_pad), lambda i: (0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((f, 3, b_pad), jnp.float32),
    )(jnp.asarray([leaf_id], dtype=jnp.int32), bins, ghc_t,
      row_leaf.reshape(1, n))
    return out.transpose(0, 2, 1)[:, :num_bins_total, :]


def masked_histograms_xla(bins, ghc_t, row_leaf, leaf_id, num_bins_total,
                          row_chunk=HIST_CHUNK):
    """Reference XLA implementation (CPU tests / non-TPU backends): the
    chunked one-hot einsum of ops/histogram.py with the leaf mask folded
    into the stats."""
    from .histogram import build_histograms
    mask = (row_leaf == leaf_id).astype(jnp.float32)
    ghc = (ghc_t * mask[None, :]).T
    return build_histograms(bins, ghc, num_bins_total, row_chunk)


def masked_histograms(bins, ghc_t, row_leaf, leaf_id, num_bins_total,
                      row_chunk=HIST_CHUNK):
    """Backend dispatch, resolved at trace time."""
    if jax.default_backend() == "tpu":
        return masked_histograms_tpu(bins, ghc_t, row_leaf, leaf_id,
                                     num_bins_total)
    return masked_histograms_xla(bins, ghc_t, row_leaf, leaf_id,
                                 num_bins_total, row_chunk)
