"""Device stable partition of a leaf-contiguous row layout.

Reference: DataPartition::Split (data_partition.hpp:100-140) — per-
thread left/right buffers merged by prefix sum keep each leaf's row
indices contiguous and in stable order. The TPU translation is the
same prefix-sum idea without threads: one vectorized pass computes
every row's destination position, and the permutation is applied as a
single scatter + gathers.

All rows of the split segment move — including out-of-bag and padding
rows (their statistics are zero, so placement is free of side effects);
the counts used by the tree remain the in-bag histogram counts.
"""

import jax.numpy as jnp


def split_destinations(go_left, begin, cnt):
    """Stable-partition destinations for the segment [begin, begin+cnt).

    Args:
      go_left: (N,) bool in CURRENT position order (only the segment's
        values matter).
      begin, cnt: traced int32 segment bounds.

    Returns (dest, n_left): dest (N,) int32 maps position p -> new
    position (identity outside the segment); n_left is the FULL left
    row count (in-bag + out-of-bag + padding).
    """
    n = go_left.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    in_seg = (pos >= begin) & (pos < begin + cnt)
    lm = in_seg & go_left
    rm = in_seg & ~go_left
    rank_l = jnp.cumsum(lm.astype(jnp.int32)) - 1  # 0-based within lm
    rank_r = jnp.cumsum(rm.astype(jnp.int32)) - 1
    n_left = rank_l[-1] + 1
    dest = jnp.where(
        lm, begin + rank_l,
        jnp.where(rm, begin + n_left + rank_r, pos)).astype(jnp.int32)
    return dest, n_left


def compact_gather_indices(mask, size):
    """Stable compaction of a row mask into gather indices.

    The gather-compacted histogram engine (ops/histogram.py
    compacted_histograms) needs the positions of one leaf's rows as a
    CONTIGUOUS index buffer of static length. This is the same
    prefix-sum rank idea as split_destinations, applied to a boolean
    mask: row p's destination is its rank among selected rows, and the
    scatter drops everything else.

    Args:
      mask: (N,) bool row selector.
      size: static buffer length; the caller guarantees
        sum(mask) <= size (bucketed dispatch, ordered_hist.bucket_sizes).

    Returns (size,) int32 `src` with the selected rows' positions in
    original order, padded with the out-of-range sentinel N (callers
    gather with a clamp and zero the padded rows' statistics).
    """
    n = mask.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    rank = jnp.cumsum(mask.astype(jnp.int32)) - 1
    dest = jnp.where(mask, rank, size)
    return (jnp.full(size, n, dtype=jnp.int32)
            .at[dest].set(pos, mode="drop"))


def invert_permutation(dest):
    """src such that new[q] = old[src[q]] given new[dest[p]] = old[p]."""
    n = dest.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    return jnp.zeros(n, jnp.int32).at[dest].set(pos)


def apply_partition(src, words, ghc_t, perm):
    """Permute the leaf-ordered arrays by the inverse permutation."""
    return (jnp.take(words, src, axis=1),
            jnp.take(ghc_t, src, axis=1),
            jnp.take(perm, src))
