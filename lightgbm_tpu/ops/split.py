"""Best-split search: vectorized cumulative scan over (feature, bin).

Reference: src/treelearner/feature_histogram.hpp:116-246 (right-to-left
threshold scan with min_data / min_sum_hessian / min_gain constraints)
and :290-313 (L1/L2-regularized gain and leaf-output formulas).

The reference scans each feature's bins serially per leaf; here every
(feature, threshold) candidate is evaluated at once with a reversed
cumulative sum, constraints become masks, and the argmax reproduces the
reference's tie-breaking: among equal gains the LARGEST threshold wins
(the serial scan runs from high t to low t and only replaces on strictly
greater gain), and across features the SMALLEST feature index wins
(SplitInfo::operator>, split_info.hpp:98-103).

Epsilon conventions replicated from the reference:
  - parent sum_hessians gets +2*kEpsilon (feature_histogram.hpp:59)
  - the right-side hessian accumulator starts at kEpsilon (:123)
  - categorical uses the raw per-bin hessian for the "current" side (:197)
"""

from typing import NamedTuple

import jax.numpy as jnp

K_EPSILON = 1e-15
K_MIN_SCORE = -jnp.inf


class SplitParams(NamedTuple):
    """Static split constraints (TreeConfig, config.h:166-186)."""
    min_data_in_leaf: float
    min_sum_hessian_in_leaf: float
    lambda_l1: float
    lambda_l2: float
    min_gain_to_split: float


class SplitInfo(NamedTuple):
    """Best split of one leaf (src/treelearner/split_info.hpp:17-104)."""
    gain: jnp.ndarray
    feature: jnp.ndarray
    threshold: jnp.ndarray
    left_sum_gradient: jnp.ndarray
    left_sum_hessian: jnp.ndarray
    left_count: jnp.ndarray
    right_sum_gradient: jnp.ndarray
    right_sum_hessian: jnp.ndarray
    right_count: jnp.ndarray
    left_output: jnp.ndarray
    right_output: jnp.ndarray


def _threshold_l1(s, l1):
    return jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_split_gain(sum_g, sum_h, l1, l2):
    """GetLeafSplitGain (feature_histogram.hpp:290-298)."""
    reg = _threshold_l1(sum_g, l1)
    return jnp.where(reg > 0.0, reg * reg / (sum_h + l2), 0.0)


def leaf_output(sum_g, sum_h, l1, l2):
    """CalculateSplittedLeafOutput (feature_histogram.hpp:306-313)."""
    reg = _threshold_l1(sum_g, l1)
    return jnp.where(reg > 0.0, -jnp.sign(sum_g) * reg / (sum_h + l2), 0.0)


def per_feature_best(hist, sum_g, sum_h, num_data,
                     num_bin_per_feature, is_categorical, feature_mask,
                     params: SplitParams):
    """Best (gain, threshold) of every feature for one leaf.

    Returns (best_gain_f, best_t): two (F,) arrays. Used directly by the
    voting-parallel learner's local top-k vote
    (voting_parallel_tree_learner.cpp:137-166) and by find_best_split.

    Args:
      hist: (F, B, 3) float32 — per (feature, bin) [sum_grad, sum_hess, count].
      sum_g, sum_h, num_data: scalar leaf totals (in-bag).
      num_bin_per_feature: (F,) int32.
      is_categorical: (F,) bool.
      feature_mask: (F,) bool — feature_fraction sampling for this tree.
      params: SplitParams.
    """
    f, b, _ = hist.shape
    g = hist[:, :, 0]
    h = hist[:, :, 1]
    c = hist[:, :, 2]

    sum_h_eps = sum_h + 2.0 * K_EPSILON
    gain_shift = leaf_split_gain(sum_g, sum_h_eps, params.lambda_l1, params.lambda_l2)
    min_gain_shift = gain_shift + params.min_gain_to_split

    # ---------------- numerical: thresholds t in [0, B-2], left = bin <= t
    rcum_g = jnp.cumsum(g[:, ::-1], axis=1)[:, ::-1]  # rcum[:, j] = sum_{b >= j}
    rcum_h = jnp.cumsum(h[:, ::-1], axis=1)[:, ::-1]
    rcum_c = jnp.cumsum(c[:, ::-1], axis=1)[:, ::-1]

    right_g = rcum_g[:, 1:]                       # (F, B-1), t = 0..B-2
    right_h = rcum_h[:, 1:] + K_EPSILON           # accumulator seed (hpp:123)
    right_c = rcum_c[:, 1:]
    left_c = num_data - right_c
    left_h = sum_h_eps - right_h
    left_g = sum_g - right_g

    num_valid = ((right_c >= params.min_data_in_leaf)
                 & (left_c >= params.min_data_in_leaf)
                 & (right_h >= params.min_sum_hessian_in_leaf)
                 & (left_h >= params.min_sum_hessian_in_leaf))
    num_gain = (leaf_split_gain(left_g, left_h, params.lambda_l1, params.lambda_l2)
                + leaf_split_gain(right_g, right_h, params.lambda_l1, params.lambda_l2))
    num_valid &= num_gain >= min_gain_shift
    num_score = jnp.where(num_valid, num_gain, K_MIN_SCORE)

    # tie-break: largest threshold -> argmax over reversed axis
    rev = num_score[:, ::-1]
    t_rev = jnp.argmax(rev, axis=1)
    num_best_t = (b - 2) - t_rev                              # (F,)
    num_best_gain = jnp.take_along_axis(num_score, num_best_t[:, None], axis=1)[:, 0]

    # ---------------- categorical: one-vs-rest on bin t (hpp:187-246)
    cur_g, cur_h_raw, cur_c = g, h, c
    oth_c = num_data - cur_c
    oth_h = sum_h_eps - cur_h_raw
    oth_g = sum_g - cur_g
    cat_valid = ((cur_c >= params.min_data_in_leaf)
                 & (oth_c >= params.min_data_in_leaf)
                 & (cur_h_raw >= params.min_sum_hessian_in_leaf)
                 & (oth_h >= params.min_sum_hessian_in_leaf))
    cat_gain = (leaf_split_gain(cur_g, cur_h_raw, params.lambda_l1, params.lambda_l2)
                + leaf_split_gain(oth_g, oth_h, params.lambda_l1, params.lambda_l2))
    cat_valid &= cat_gain >= min_gain_shift
    cat_score = jnp.where(cat_valid, cat_gain, K_MIN_SCORE)
    cat_t_rev = jnp.argmax(cat_score[:, ::-1], axis=1)
    cat_best_t = (b - 1) - cat_t_rev
    cat_best_gain = jnp.take_along_axis(cat_score, cat_best_t[:, None], axis=1)[:, 0]

    # ---------------- merge numerical/categorical per feature
    best_t = jnp.where(is_categorical, cat_best_t, num_best_t).astype(jnp.int32)
    best_gain_f = jnp.where(is_categorical, cat_best_gain, num_best_gain)
    best_gain_f = jnp.where(feature_mask, best_gain_f, K_MIN_SCORE)
    return best_gain_f, best_t


def find_best_split(hist, sum_g, sum_h, num_data,
                    num_bin_per_feature, is_categorical, feature_mask,
                    params: SplitParams) -> SplitInfo:
    """Best split over all features of one leaf (see per_feature_best)."""
    best_gain_f, best_t = per_feature_best(
        hist, sum_g, sum_h, num_data, num_bin_per_feature, is_categorical,
        feature_mask, params)
    # across features: first max = smallest feature id (matches SplitInfo tie-break)
    best_f = jnp.argmax(best_gain_f).astype(jnp.int32)
    return split_info_at(hist, sum_g, sum_h, num_data, is_categorical, params,
                         best_f, best_t[best_f], best_gain_f[best_f])


def split_info_at(hist, sum_g, sum_h, num_data, is_categorical, params,
                  best_f, best_thr, best_gain) -> SplitInfo:
    """Reconstruct the full SplitInfo of a chosen (feature, threshold)."""
    g = hist[:, :, 0]
    h = hist[:, :, 1]
    c = hist[:, :, 2]
    sum_h_eps = sum_h + 2.0 * K_EPSILON
    gain_shift = leaf_split_gain(sum_g, sum_h_eps, params.lambda_l1, params.lambda_l2)
    rcum_g = jnp.cumsum(g[:, ::-1], axis=1)[:, ::-1]
    rcum_h = jnp.cumsum(h[:, ::-1], axis=1)[:, ::-1]
    rcum_c = jnp.cumsum(c[:, ::-1], axis=1)[:, ::-1]

    b = hist.shape[1]
    is_cat = is_categorical[best_f]
    # numerical left/right at (best_f, best_thr)
    thr_next = jnp.minimum(best_thr + 1, b - 1)
    n_right_g = rcum_g[best_f, thr_next]
    n_right_h = rcum_h[best_f, thr_next] + K_EPSILON
    n_right_c = rcum_c[best_f, thr_next]
    n_left_g = sum_g - n_right_g
    n_left_h = sum_h_eps - n_right_h
    n_left_c = num_data - n_right_c
    # categorical: left = the chosen bin, right = rest
    c_left_g = g[best_f, best_thr]
    c_left_h = h[best_f, best_thr]
    c_left_c = c[best_f, best_thr]
    c_right_g = sum_g - c_left_g
    c_right_h = sum_h_eps - c_left_h
    c_right_c = num_data - c_left_c

    lg = jnp.where(is_cat, c_left_g, n_left_g)
    lh = jnp.where(is_cat, c_left_h, n_left_h)
    lc = jnp.where(is_cat, c_left_c, n_left_c)
    rg = jnp.where(is_cat, c_right_g, n_right_g)
    rh = jnp.where(is_cat, c_right_h, n_right_h)
    rc = jnp.where(is_cat, c_right_c, n_right_c)

    lout = leaf_output(lg, lh, params.lambda_l1, params.lambda_l2)
    rout = leaf_output(rg, rh, params.lambda_l1, params.lambda_l2)

    found = best_gain > K_MIN_SCORE
    out_gain = jnp.where(found, best_gain - gain_shift, K_MIN_SCORE)

    return SplitInfo(
        gain=out_gain,
        feature=best_f,
        threshold=best_thr,
        left_sum_gradient=lg, left_sum_hessian=lh, left_count=lc,
        right_sum_gradient=rg, right_sum_hessian=rh, right_count=rc,
        left_output=lout, right_output=rout,
    )
