"""Histogram construction: the hot op of GBDT training.

Reference: the per-feature scalar accumulation loops in
src/io/dense_bin.hpp:16-195 (4-way unrolled CPU scatter-add) and
src/treelearner/feature_histogram.hpp:54-79.

TPU-first design: scatter-add does not vectorize on TPU; instead the
histogram is ONE batched one-hot contraction on the MXU:

    hist[f, b, k] = sum_n [bins[f, n] == b] * ghc[n, k]

where ghc packs the per-row statistics columns (gradient, hessian,
in-leaf count mask — and both children at once: the reference's
"histogram subtraction trick" (serial_tree_learner.cpp:376-379) halves
CPU work; on the MXU both children ride in the same matmul for free
because the stat-column dimension sits far below the 128-lane tile, so
left and right child histograms come out of one pass).

Rows are processed in chunks via `lax.scan` so the one-hot operand
stays small; XLA fuses the compare into the dot operand tiles.

Per-chunk kernel dispatch: the one-hot contraction is O(C * F * B)
compares — right for the MXU, wasteful on CPU where XLA lowers a
segment-sum to the reference's own scatter-add loop at O(C * F * K).
`_hist_chunk` therefore picks the formulation by backend (measured ~2x
on this image's CPU at bench shape); LIGHTGBM_TPU_HIST_MODE forces
either. Chunk results are identical up to f32 summation order.

Smaller-child compaction (compacted_histograms): the default dense
training path (models/tree_learner.py) gathers the active leaf's rows
into a contiguous bucket-padded buffer first — per-split cost
O(rows-in-child), not O(N) — reusing the geometric bucket machinery of
ops/ordered_hist.py for static shapes under jit. This is the gather
analog of XGBoost-GPU/ThunderGBM's row compaction before the histogram
scatter (arXiv:1806.11248 §4.2, arXiv:1706.08359 §5).
"""

import os

import jax
import jax.numpy as jnp

from .ordered_hist import bucket_sizes, cover_index
from .pallas_hist import HIST_CHUNK

DEFAULT_ROW_CHUNK = 8192


def _parse_hist_mode():
    raw = os.environ.get("LIGHTGBM_TPU_HIST_MODE", "auto").lower()
    if raw not in ("auto", "einsum", "segment"):
        # import-time knob: warn and fall back rather than taking down
        # an embedder that only wanted prediction
        from ..utils.log import Log
        Log.warning("LIGHTGBM_TPU_HIST_MODE must be auto, einsum or "
                    "segment, got [%s]; using auto", raw)
        return "auto"
    return raw


# Chunk-kernel formulation, read ONCE at import (jitted programs bake
# it in): "einsum" = one-hot MXU contraction, "segment" = scatter-add
# segment sum, "auto" = segment on CPU, einsum elsewhere.
HIST_MODE = _parse_hist_mode()


def build_histograms(bins, ghc, num_bins_total, row_chunk=DEFAULT_ROW_CHUNK):
    """Compute per-feature histograms of the packed row statistics.

    Args:
      bins: (F, N) integer bin matrix (uint8/uint16), N a multiple of
        row_chunk when N > row_chunk (pad rows must carry ghc == 0).
      ghc: (N, K) float32 packed statistics; masked rows are zero.
      num_bins_total: static int B — histogram width (max bins over features).
      row_chunk: static chunk size for the scan.

    Returns:
      (F, B, K) float32 histogram.
    """
    hi, lo = build_histograms_pair(bins, ghc, num_bins_total, row_chunk)
    return hi + lo


def build_histograms_pair(bins, ghc, num_bins_total, row_chunk=DEFAULT_ROW_CHUNK):
    """Compensated (Kahan) accumulation across row chunks: returns the
    (value, compensation) float32 pair, summing per-chunk f32 partials
    with ~f64-equivalent accuracy. The pair representation lets the
    data-parallel learner reduce shard partials in a FIXED order
    (ops-level analog of the reference's f64 accumulators, bin.h:18-26),
    so serial and data-parallel training see histograms that agree to
    ~1e-14 relative instead of f32-reduction-order ulps."""
    f, n = bins.shape
    k = ghc.shape[1]
    b = num_bins_total

    if n <= row_chunk:
        h = _hist_chunk(bins, ghc, b)
        return h, jnp.zeros_like(h)
    if n % row_chunk != 0:
        raise ValueError(f"N={n} must be padded to a multiple of {row_chunk}")
    nchunks = n // row_chunk

    bins_c = bins.reshape(f, nchunks, row_chunk).transpose(1, 0, 2)
    ghc_c = ghc.reshape(nchunks, row_chunk, k)

    def step(carry, xs):
        acc, comp = carry
        bc, gc = xs
        h = _hist_chunk(bc, gc, b)
        y = h - comp
        t = acc + y
        comp = (t - acc) - y
        return (t, comp), None

    zero = jnp.zeros((f, b, k), dtype=jnp.float32)
    (acc, comp), _ = jax.lax.scan(step, (zero, zero), (bins_c, ghc_c))
    return acc, -comp  # Kahan comp holds the NEGATIVE residual


def _hist_chunk(bins_chunk, ghc_chunk, b):
    """One row chunk -> (F, B, K) partial histogram; formulation by
    backend (HIST_MODE)."""
    mode = HIST_MODE
    if mode == "auto":
        mode = "segment" if jax.default_backend() == "cpu" else "einsum"
    if mode == "segment":
        return _hist_chunk_segment(bins_chunk, ghc_chunk, b)
    return _hist_chunk_einsum(bins_chunk, ghc_chunk, b)


def _hist_chunk_einsum(bins_chunk, ghc_chunk, b):
    """One-hot contraction over a row chunk: (F, C), (C, K) -> (F, B, K)."""
    onehot = (bins_chunk[:, :, None] == jnp.arange(b, dtype=jnp.int32)[None, None, :])
    return jnp.einsum("fcb,ck->fbk", onehot.astype(jnp.float32),
                      ghc_chunk.astype(jnp.float32),
                      preferred_element_type=jnp.float32)


def _hist_chunk_segment(bins_chunk, ghc_chunk, b):
    """Scatter-add formulation: XLA CPU lowers segment_sum to the
    reference's own per-row accumulation loop (dense_bin.hpp:16-195),
    O(C * K) per feature instead of the one-hot's O(C * B)."""
    ghc_f32 = ghc_chunk.astype(jnp.float32)

    def one(bf):
        return jax.ops.segment_sum(ghc_f32, bf.astype(jnp.int32),
                                   num_segments=b)

    return jax.vmap(one)(bins_chunk)


def compacted_histograms(bins, ghc_t, row_leaf, leaf_id, num_bins_total,
                         row_chunk=HIST_CHUNK):
    """Gather-compacted leaf histogram: cost scales with the leaf's row
    count, not the dataset.

    The leaf's rows (selected on the dense row->leaf map, original
    order preserved) are compacted into a contiguous buffer whose
    static length is the geometric chunk bucket covering the leaf's row
    count (ops/ordered_hist.py bucket_sizes / cover_index — the same
    dispatch the leaf-contiguous builder uses for position ranges), and
    only that buffer feeds the chunked Kahan accumulation. Rows past
    the count gather arbitrary bins with ZERO statistics, so padding
    never perturbs the histogram.

    Args:
      bins: (F, N) integer bin matrix, N % HIST_CHUNK == 0.
      ghc_t: (3, N) float32 stats (grad*inbag, hess*inbag, inbag);
        padding rows must be zero.
      row_leaf: (N,) int32 row->leaf map.
      leaf_id: traced int32 scalar.
      num_bins_total: static histogram width B.
      row_chunk: static scan chunk of the compacted buffer.

    Returns the compensated (value, residual) pair of
    build_histograms_pair — collapse with `hi + lo`, or reduce shard
    pairs in fixed order first (parallel/learners.py pair_allreduce;
    the lax.switch holds no collectives, so shards on different buckets
    still meet the reduction in lockstep).
    """
    from .partition import compact_gather_indices
    f, n = bins.shape
    if n % HIST_CHUNK != 0:
        raise ValueError(f"N={n} must be a multiple of {HIST_CHUNK}")
    n_chunks = n // HIST_CHUNK
    buckets = bucket_sizes(n_chunks)
    chunk = min(int(row_chunk), HIST_CHUNK)

    mask = row_leaf == leaf_id
    cnt = jnp.sum(mask.astype(jnp.int32))
    idx, _ = cover_index(jnp.int32(0), cnt, n_chunks)

    def make_branch(bk):
        size = bk * HIST_CHUNK

        def branch(mask):
            src = compact_gather_indices(mask, size)
            valid = (src < n).astype(jnp.float32)
            src_c = jnp.minimum(src, n - 1)
            bins_sl = jnp.take(bins, src_c, axis=1)
            ghc_sl = jnp.take(ghc_t, src_c, axis=1) * valid[None, :]
            return build_histograms_pair(bins_sl, ghc_sl.T, num_bins_total,
                                         row_chunk=min(size, chunk))

        return branch

    return jax.lax.switch(idx, [make_branch(b) for b in buckets], mask)
