"""Histogram construction: the hot op of GBDT training.

Reference: the per-feature scalar accumulation loops in
src/io/dense_bin.hpp:16-195 (4-way unrolled CPU scatter-add) and
src/treelearner/feature_histogram.hpp:54-79.

TPU-first design: scatter-add does not vectorize on TPU; instead the
histogram is ONE batched one-hot contraction on the MXU:

    hist[f, b, k] = sum_n [bins[f, n] == b] * ghc[n, k]

where ghc packs the per-row statistics columns (gradient, hessian,
in-leaf count mask — and both children at once: the reference's
"histogram subtraction trick" (serial_tree_learner.cpp:376-379) halves
CPU work; on the MXU both children ride in the same matmul for free
because the stat-column dimension sits far below the 128-lane tile, so
left and right child histograms come out of one pass).

Rows are processed in chunks via `lax.scan` so the one-hot operand
stays small; XLA fuses the compare into the dot operand tiles.

Chunk-kernel selection (`hist_mode`, config knob + LIGHTGBM_TPU_HIST_MODE
env, resolved by `chunk_mode()` / `use_pallas()`):

- "pallas"  — the Pallas TPU streaming kernels (ops/pallas_hist.py /
  ops/ordered_hist.py). The auto default on TPU.
- "bincount" — per-chunk f64 `np.bincount` on host via
  `jax.pure_callback`. XLA's CPU scatter lowering costs ~60 ns per
  row-feature regardless of formulation (measured on this image);
  numpy's C bincount loop runs the same scatter at ~13 ns AND
  accumulates in f64 (better than the f32 in-chunk order the XLA
  segment path gives). The auto default on CPU. The callback keeps the
  CHUNK-ALIGNED Kahan pair structure (see build_histograms_pair), so
  the serial == data-parallel agreement guarantee is unchanged: a
  chunk's f32 partial depends only on the chunk's rows, and the pair
  combination order is identical on every shard.
- "segment" — jax.ops.segment_sum scatter-add: the XLA-native CPU
  formulation (the reference's own per-row accumulation loop,
  dense_bin.hpp:16-195). Fallback when callbacks are unwanted
  (e.g. profiling pure-XLA programs).
- "einsum" — the one-hot MXU contraction: right where compares are
  cheaper than scatters (non-TPU accelerators, TPU XLA fallback).

A non-auto mode forces that formulation everywhere it can run (pallas
off-TPU falls back with a warning; einsum/segment/bincount on TPU
disable the Pallas kernels — the supported escape hatch, superseding
LIGHTGBM_TPU_DISABLE_PALLAS which remains honored).

Smaller-child compaction (compacted_histograms): the default dense
training path (models/tree_learner.py) gathers the active leaf's rows
into a contiguous bucket-padded buffer first — per-split cost
O(rows-in-child), not O(N) — reusing the geometric bucket machinery of
ops/ordered_hist.py for static shapes under jit. This is the gather
analog of XGBoost-GPU/ThunderGBM's row compaction before the histogram
scatter (arXiv:1806.11248 §4.2, arXiv:1706.08359 §5).

Frontier batching (frontier_histograms): one data pass builds the
histograms of a STATIC VECTOR of leaves at once — a combined
leaf x feature x bin key on the bincount/segment paths, a leaf-indexed
accumulator in the Pallas kernel (ops/pallas_hist.py). Used for the
root/bagging re-init pass of every tree and for both children of a
split in the cache-less (memory-bounded) builder, which halves its
full-matrix streams (docs/Histogram-Engine.md).
"""

import contextlib
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from .ordered_hist import bucket_sizes, cover_index
from .pallas_hist import HIST_CHUNK

DEFAULT_ROW_CHUNK = 8192


def _roofline_record(kernel, seconds, nbytes, rows):
    """Live roofline attribution (telemetry/roofline.py): the bincount
    host callbacks are the one place kernel execution is host-observable
    (they ARE the kernel on the CPU default path), so each call records
    its (wall seconds, bytes streamed, rows scanned) here. One O(1)
    table update per histogram build — far below the <1% telemetry bar.
    In-graph kernels (pallas/einsum/segment) are invisible to host
    timers inside one XLA program; they stay covered by the bench's
    single-op microprobes."""
    from ..telemetry.roofline import TABLE
    TABLE.record(kernel, seconds, nbytes, rows)

_HIST_MODES = ("auto", "pallas", "einsum", "segment", "bincount")


def _parse_hist_mode():
    raw = os.environ.get("LIGHTGBM_TPU_HIST_MODE", "auto").lower()
    if raw not in _HIST_MODES:
        # import-time knob: warn and fall back rather than taking down
        # an embedder that only wanted prediction
        from ..utils.log import Log
        Log.warning("LIGHTGBM_TPU_HIST_MODE must be one of %s, got [%s]; "
                    "using auto", "/".join(_HIST_MODES), raw)
        return "auto"
    return raw


# Chunk-kernel formulation. Initialized from the env once at import;
# config-level `hist_mode` overrides it at learner init (set_hist_mode).
# Jitted programs bake the resolved mode in: changing it invalidates
# builders compiled earlier in the process (same contract the env knob
# always had).
_DEFAULT_HIST_MODE = _parse_hist_mode()
HIST_MODE = _DEFAULT_HIST_MODE
_WARNED_PALLAS_FALLBACK = False


def set_hist_mode(mode):
    """Set the process-wide histogram formulation from config
    (models/tree_learner.py init). "auto" RESTORES the env-derived
    process default (LIGHTGBM_TPU_HIST_MODE or auto), so one Booster's
    forced mode never leaks into the next Booster's."""
    global HIST_MODE, _WARNED_PALLAS_FALLBACK
    mode = str(mode).lower()
    if mode not in _HIST_MODES:
        from ..utils.log import Log
        Log.fatal("hist_mode must be one of %s, got [%s]",
                  "/".join(_HIST_MODES), mode)
    HIST_MODE = _DEFAULT_HIST_MODE if mode == "auto" else mode
    if (HIST_MODE == "pallas" and jax.default_backend() != "tpu"
            and not _WARNED_PALLAS_FALLBACK):
        from ..utils.log import Log
        Log.warning("hist_mode=pallas needs a TPU backend (got %s); "
                    "falling back to the auto formulation",
                    jax.default_backend())
        _WARNED_PALLAS_FALLBACK = True


def use_pallas():
    """Whether the Pallas TPU kernels are the active histogram engine
    (resolved at trace time). True only on a real TPU backend with
    hist_mode auto/pallas and the legacy escape hatch unset."""
    if jax.default_backend() != "tpu":
        return False
    if os.environ.get("LIGHTGBM_TPU_DISABLE_PALLAS"):
        return False
    return HIST_MODE in ("auto", "pallas")


_NO_CALLBACKS = threading.local()


@contextlib.contextmanager
def callbacks_disabled():
    """Trace-time guard: inside this context, "bincount" resolves to
    the XLA segment kernel. Host callbacks embedded in MULTI-DEVICE
    shard_map programs can deadlock this image's XLA CPU runtime (the
    dispatching thread blocks in a sharded execute while the callback
    worker threads park on the GIL it holds — observed as a hang in
    the data-parallel compacted build, single-device programs are
    unaffected), so the meshed learners trace their builders under
    this guard (parallel/mesh.py meshed_trace_guard)."""
    depth = getattr(_NO_CALLBACKS, "depth", 0)
    _NO_CALLBACKS.depth = depth + 1
    try:
        yield
    finally:
        _NO_CALLBACKS.depth = depth


def single_worker_host():
    """True when this process is pinned to a single CPU (checked per
    call so tests can flip it with sched_setaffinity)."""
    try:
        n = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):  # non-Linux fallback
        n = os.cpu_count() or 1
    return n <= 1


def host_callbacks_hazardous():
    """Whether an async-dispatched jit program embedding pure_callback
    can deadlock this process's XLA CPU client. Observed on 1-core
    runners with a single (non-virtualized) CPU device: the client's
    lone worker executes the builder program while the callback's
    operand delivery waits for that same thread — the compacted
    learner's per-iteration path wedges at n > HIST_CHUNK (where
    hist_compaction auto-enables the frontier/compacted callbacks; the
    PR 14 cliff). Forcing >= 2 virtual CPU devices
    (--xla_force_host_platform_device_count, what the test harness and
    bench children do) gives the callback a worker and clears it, as
    does the AOT-compiled fused block (models/gbdt.py _get_fused_fn),
    so the hazard is exactly {1 CPU} x {1 local device} x traced-jit
    dispatch. The serial learner's train_device consults this and
    traces its builder under callbacks_disabled (segment kernel:
    bit-identical per the pinned segment==bincount parity, slower, but
    today that configuration hangs forever)."""
    return single_worker_host() and jax.local_device_count() == 1


def chunk_mode():
    """Resolve the XLA/host chunk-kernel formulation:
    "bincount" | "segment" | "einsum"."""
    mode = HIST_MODE
    if mode in ("auto", "pallas"):
        # pallas off-TPU falls back like auto (the kernels cannot run);
        # on TPU this path is only reached for XLA fallbacks
        mode = ("bincount" if jax.default_backend() == "cpu"
                else "einsum")
    if mode == "bincount" and getattr(_NO_CALLBACKS, "depth", 0):
        return "segment"  # see callbacks_disabled
    return mode


def build_histograms(bins, ghc, num_bins_total, row_chunk=DEFAULT_ROW_CHUNK):
    """Compute per-feature histograms of the packed row statistics.

    Args:
      bins: (F, N) integer bin matrix (uint8/int16), N a multiple of
        row_chunk when N > row_chunk (pad rows must carry ghc == 0).
      ghc: (N, K) float32 packed statistics; masked rows are zero.
      num_bins_total: static int B — histogram width (max bins over features).
      row_chunk: static chunk size for the scan.

    Returns:
      (F, B, K) float32 histogram.
    """
    hi, lo = build_histograms_pair(bins, ghc, num_bins_total, row_chunk)
    return hi + lo


def build_histograms_pair(bins, ghc, num_bins_total, row_chunk=DEFAULT_ROW_CHUNK):
    """Compensated (Kahan) accumulation across row chunks: returns the
    (value, compensation) float32 pair, summing per-chunk f32 partials
    with ~f64-equivalent accuracy. The pair representation lets the
    data-parallel learner reduce shard partials in a FIXED order
    (ops-level analog of the reference's f64 accumulators, bin.h:18-26),
    so serial and data-parallel training see histograms that agree to
    ~1e-14 relative instead of f32-reduction-order ulps.

    All chunk modes share this structure: a chunk's f32 partial is a
    pure function of the chunk's rows, and partials combine in chunk
    order — the property the serial == parallel contract rests on. The
    bincount mode runs the whole chunk loop in ONE host callback
    (per-call numpy overhead ~1 us; the Kahan arithmetic is mirrored in
    f32 numpy, bit-identical to the lax.scan version)."""
    if chunk_mode() == "bincount":
        return _hist_pair_bincount(bins, ghc, num_bins_total, row_chunk)
    f, n = bins.shape
    k = ghc.shape[1]
    b = num_bins_total

    if n <= row_chunk:
        h = _hist_chunk(bins, ghc, b)
        return h, jnp.zeros_like(h)
    if n % row_chunk != 0:
        raise ValueError(f"N={n} must be padded to a multiple of {row_chunk}")
    nchunks = n // row_chunk

    bins_c = bins.reshape(f, nchunks, row_chunk).transpose(1, 0, 2)
    ghc_c = ghc.reshape(nchunks, row_chunk, k)

    def step(carry, xs):
        acc, comp = carry
        bc, gc = xs
        h = _hist_chunk(bc, gc, b)
        y = h - comp
        t = acc + y
        comp = (t - acc) - y
        return (t, comp), None

    zero = jnp.zeros((f, b, k), dtype=jnp.float32)
    (acc, comp), _ = jax.lax.scan(step, (zero, zero), (bins_c, ghc_c))
    return acc, -comp  # Kahan comp holds the NEGATIVE residual


def hist_pair_fold_block(acc, comp, bins_blk, ghc_blk, num_bins_total,
                         row_chunk=DEFAULT_ROW_CHUNK):
    """Continue build_histograms_pair's Kahan chunk scan across a block
    boundary: fold `bins_blk`'s chunks into the running (acc, comp)
    carry and return the new carry. Because a chunk's f32 partial
    depends only on the chunk's rows and the carry chain is strictly
    sequential, folding row-ordered blocks whose boundaries land on the
    chunk grid reproduces the single-pass scan BIT-FOR-BIT — the
    out-of-core streaming engine's parity contract
    (lightgbm_tpu/data/ooc_learner.py; collapse the final carry with
    hist_pair_fold_collapse).

    Args:
      acc, comp: (F, B, K) float32 running Kahan value/compensation
        (start both at zeros; `comp` is the NEGATIVE residual, Kahan's
        internal convention — build_histograms_pair returns -comp).
      bins_blk: (F, R) integer bins, R a multiple of row_chunk (or a
        single chunk when R <= row_chunk).
      ghc_blk: (R, K) float32 packed statistics.
    """
    f, n = bins_blk.shape
    k = ghc_blk.shape[1]
    if n <= row_chunk:
        chunks = (bins_blk[None], ghc_blk[None])
    else:
        if n % row_chunk != 0:
            raise ValueError(
                f"block of {n} rows must be a multiple of the scan "
                f"chunk {row_chunk}")
        nchunks = n // row_chunk
        chunks = (bins_blk.reshape(f, nchunks, row_chunk)
                  .transpose(1, 0, 2),
                  ghc_blk.reshape(nchunks, row_chunk, k))

    def step(carry, xs):
        acc, comp = carry
        bc, gc = xs
        h = _hist_chunk(bc, gc, num_bins_total)
        y = h - comp
        t = acc + y
        comp = (t - acc) - y
        return (t, comp), None

    (acc, comp), _ = jax.lax.scan(step, (acc, comp), chunks)
    return acc, comp


def hist_pair_fold_collapse(acc, comp):
    """Collapse a hist_pair_fold_block carry into the final histogram —
    the same `value + (-residual)` f32 add as _collapse_pair applied to
    build_histograms_pair's (acc, -comp) output."""
    return acc + (-comp)


def _chunk_bounds(n, row_chunk):
    """Chunk decomposition shared by the XLA scan and the bincount
    callback: one chunk when n <= row_chunk, else n/row_chunk chunks."""
    if n <= row_chunk:
        return 1, n
    if n % row_chunk != 0:
        raise ValueError(f"N={n} must be padded to a multiple of {row_chunk}")
    return n // row_chunk, row_chunk


def _bincount_chunk_loop(nchunks, shape, chunk_fn):
    """Numpy mirror of build_histograms_pair's Kahan chunk scan.
    `chunk_fn(ci)` -> the chunk's f32 partial of `shape`. Returns the
    stacked (2, *shape) [value, residual] f32 pair."""
    acc = np.zeros(shape, np.float32)
    comp = np.zeros(shape, np.float32)
    for ci in range(nchunks):
        h = chunk_fn(ci)
        y = h - comp
        t = acc + y
        comp = (t - acc) - y
        acc = t
    # (-comp) + 0.0 canonicalizes -0.0 residuals to +0.0, matching the
    # single-chunk XLA path's jnp.zeros_like
    return np.stack([acc, (-comp) + 0.0])


def _hist_pair_bincount(bins, ghc, b, row_chunk):
    """f64 np.bincount chunk kernel via pure_callback (see module
    docstring). The combined feature x bin key turns the whole chunk
    into K weighted bincounts; each chunk's f64 total rounds to the f32
    partial that feeds the Kahan pair, so the pair CONTRACT (chunk-
    aligned partials, fixed combine order) is preserved exactly."""
    f, n = bins.shape
    k = ghc.shape[1]
    nchunks, c = _chunk_bounds(n, row_chunk)

    def cb(bins_h, ghc_h):
        t_start = time.perf_counter()
        bins_h = np.asarray(bins_h)
        ghc_h = np.asarray(ghc_h, dtype=np.float64)
        base = (np.arange(f, dtype=np.int64) * b)[:, None]
        fb = f * b

        def one_chunk(ci):
            sl = slice(ci * c, (ci + 1) * c)
            key = (base + bins_h[:, sl]).ravel()
            out = np.empty((fb, k), np.float64)
            for j in range(k):
                out[:, j] = np.bincount(key,
                                        weights=np.tile(ghc_h[sl, j], f),
                                        minlength=fb)
            return out.astype(np.float32).reshape(f, b, k)

        res = _bincount_chunk_loop(nchunks, (f, b, k), one_chunk)
        _roofline_record("bincount_masked",
                         time.perf_counter() - t_start,
                         bins_h.nbytes + ghc_h.nbytes, n)
        return res

    out = jax.pure_callback(
        cb, jax.ShapeDtypeStruct((2, f, b, k), jnp.float32), bins, ghc,
        vmap_method="sequential")
    return out[0], out[1]


def _hist_chunk(bins_chunk, ghc_chunk, b):
    """One row chunk -> (F, B, K) partial histogram; XLA formulation by
    backend (chunk_mode; the bincount mode is handled a level up so the
    whole chunk loop rides one callback)."""
    if chunk_mode() == "segment":
        return _hist_chunk_segment(bins_chunk, ghc_chunk, b)
    return _hist_chunk_einsum(bins_chunk, ghc_chunk, b)


def _hist_chunk_einsum(bins_chunk, ghc_chunk, b):
    """One-hot contraction over a row chunk: (F, C), (C, K) -> (F, B, K)."""
    onehot = (bins_chunk.astype(jnp.int32)[:, :, None]
              == jnp.arange(b, dtype=jnp.int32)[None, None, :])
    return jnp.einsum("fcb,ck->fbk", onehot.astype(jnp.float32),
                      ghc_chunk.astype(jnp.float32),
                      preferred_element_type=jnp.float32)


def _hist_chunk_segment(bins_chunk, ghc_chunk, b):
    """Scatter-add formulation: XLA CPU lowers segment_sum to the
    reference's own per-row accumulation loop (dense_bin.hpp:16-195),
    O(C * K) per feature instead of the one-hot's O(C * B)."""
    ghc_f32 = ghc_chunk.astype(jnp.float32)

    def one(bf):
        return jax.ops.segment_sum(ghc_f32, bf.astype(jnp.int32),
                                   num_segments=b)

    return jax.vmap(one)(bins_chunk)


def frontier_histograms(bins, ghc_t, row_leaf, leaf_ids, num_bins_total,
                        row_chunk=HIST_CHUNK):
    """Multi-leaf histograms: ONE pass over the bin matrix builds the
    histograms of every leaf in `leaf_ids` (static length L, distinct
    ids; rows outside the frontier contribute nowhere).

    The frontier-batching primitive of docs/Histogram-Engine.md:
    - bincount mode: a combined (leaf, feature, bin) key — the leaf
      position costs one binary search per row, then the pass is the
      same K weighted bincounts as the single-leaf kernel.
    - Pallas (TPU): a leaf-indexed accumulator kernel streams the bin
      matrix once into an (L, F, B, 3) VMEM output
      (ops/pallas_hist.py frontier_histograms_tpu).
    - einsum/segment fallback: one masked pass per leaf (reads bins L
      times — these modes are non-default everywhere this primitive is
      hot).

    Per-leaf values are BITWISE what the single-leaf masked kernel
    produces for the same rows (same chunk decomposition, same
    accumulation order; zero-weight rows cannot perturb an f64 or f32
    sum), so callers may mix the two freely.

    Args:
      bins: (F, N) integer bin matrix (uint8/int16/int32).
      ghc_t: (3, N) float32 stats (grad*inbag, hess*inbag, inbag).
      row_leaf: (N,) int32 row->leaf map.
      leaf_ids: (L,) int32 DISTINCT leaf ids; L static.
      num_bins_total: static histogram width B.
      row_chunk: static chunk size of the pair scan.

    Returns the compensated ((L, F, B, 3) value, residual) pair —
    same contract as build_histograms_pair / masked_histograms.
    """
    b = num_bins_total
    if use_pallas():
        from .pallas_hist import frontier_histograms_tpu
        return frontier_histograms_tpu(bins, ghc_t, row_leaf, leaf_ids, b)
    if chunk_mode() == "bincount":
        return _frontier_pair_bincount(bins, ghc_t, row_leaf, leaf_ids, b,
                                       row_chunk)

    # einsum/segment fallback: the masked single-leaf pass per leaf
    def one(lid):
        mask = (row_leaf == lid).astype(jnp.float32)
        return build_histograms_pair(bins, (ghc_t * mask[None, :]).T, b,
                                     row_chunk)

    his, los = jax.vmap(one)(leaf_ids.astype(jnp.int32))
    return his, los


def _frontier_pair_bincount(bins, ghc_t, row_leaf, leaf_ids, b, row_chunk):
    """Combined-key bincount frontier pass. Key layout:
    pos(row) * F * B + f * B + bin, with pos(row) == L for rows outside
    the frontier (their segment is sliced off)."""
    l = leaf_ids.shape[0]
    f, n = bins.shape
    k = ghc_t.shape[0]
    nchunks, c = _chunk_bounds(n, row_chunk)

    def cb(bins_h, ghc_h, rl_h, lids_h):
        t_start = time.perf_counter()
        bins_h = np.asarray(bins_h)
        ghc_h = np.asarray(ghc_h, dtype=np.float64)
        rl_h = np.asarray(rl_h)
        lids_h = np.asarray(lids_h, dtype=np.int64)
        # leaf id -> position in leaf_ids (L = not in frontier)
        order = np.argsort(lids_h, kind="stable")
        sorted_ids = lids_h[order]
        idx = np.searchsorted(sorted_ids, rl_h)
        idxc = np.minimum(idx, l - 1)
        pos = np.where(sorted_ids[idxc] == rl_h, order[idxc],
                       np.int64(l))
        fb = f * b
        row_off = pos * fb                                    # (N,)
        base = (np.arange(f, dtype=np.int64) * b)[:, None]

        def one_chunk(ci):
            sl = slice(ci * c, (ci + 1) * c)
            key = (row_off[sl][None, :] + base + bins_h[:, sl]).ravel()
            out = np.empty(((l + 1) * fb, k), np.float64)
            for j in range(k):
                out[:, j] = np.bincount(key,
                                        weights=np.tile(ghc_h[j, sl], f),
                                        minlength=(l + 1) * fb)
            return out[:l * fb].astype(np.float32).reshape(l, f, b, k)

        res = _bincount_chunk_loop(nchunks, (l, f, b, k), one_chunk)
        _roofline_record("bincount_frontier",
                         time.perf_counter() - t_start,
                         bins_h.nbytes + ghc_h.nbytes + rl_h.nbytes, n)
        return res

    out = jax.pure_callback(
        cb, jax.ShapeDtypeStruct((2, l, f, b, k), jnp.float32),
        bins, ghc_t, row_leaf, leaf_ids, vmap_method="sequential")
    return out[0], out[1]


def _compacted_bincount(bins, ghc_t, row_leaf, leaf_id, b, chunk):
    """Host-side gather-compacted bincount: the leaf's rows are
    selected (original order, matching compact_gather_indices), sliced
    into `chunk`-row pieces (the last one ragged — no bucket padding),
    and each piece's f64 bincount feeds the f32 Kahan pair. Cost is
    O(rows-in-leaf) with no O(N) device-side compaction machinery."""
    f, n = bins.shape
    k = ghc_t.shape[0]

    def cb(bins_h, ghc_h, rl_h, lid_h):
        t_start = time.perf_counter()
        bins_h = np.asarray(bins_h)
        ghc_h = np.asarray(ghc_h, dtype=np.float64)
        rl_h = np.asarray(rl_h)
        src = np.flatnonzero(rl_h == lid_h)
        base = (np.arange(f, dtype=np.int64) * b)[:, None]
        fb = f * b
        nchunks = max(-(-len(src) // chunk), 1)

        def one_chunk(ci):
            sl = src[ci * chunk:(ci + 1) * chunk]
            key = (base + bins_h[:, sl]).ravel()
            g_sl = ghc_h[:, sl]
            out = np.empty((fb, k), np.float64)
            for j in range(k):
                out[:, j] = np.bincount(key,
                                        weights=np.tile(g_sl[j], f),
                                        minlength=fb)
            return out.astype(np.float32).reshape(f, b, k)

        res = _bincount_chunk_loop(nchunks, (f, b, k), one_chunk)
        # bytes actually streamed: the full row->leaf scan plus the
        # GATHERED bins/stats columns (cost scales with the leaf)
        touched = (rl_h.nbytes
                   + len(src) * (f * bins_h.itemsize + k * ghc_h.itemsize))
        _roofline_record("bincount_compacted",
                         time.perf_counter() - t_start,
                         touched, len(src))
        return res

    out = jax.pure_callback(
        cb, jax.ShapeDtypeStruct((2, f, b, k), jnp.float32),
        bins, ghc_t, row_leaf, leaf_id, vmap_method="sequential")
    return out[0], out[1]


def compacted_histograms(bins, ghc_t, row_leaf, leaf_id, num_bins_total,
                         row_chunk=HIST_CHUNK):
    """Gather-compacted leaf histogram: cost scales with the leaf's row
    count, not the dataset.

    The leaf's rows (selected on the dense row->leaf map, original
    order preserved) are compacted into a contiguous buffer whose
    static length is the geometric chunk bucket covering the leaf's row
    count (ops/ordered_hist.py bucket_sizes / cover_index — the same
    dispatch the leaf-contiguous builder uses for position ranges), and
    only that buffer feeds the chunked Kahan accumulation. Rows past
    the count gather arbitrary bins with ZERO statistics, so padding
    never perturbs the histogram.

    Args:
      bins: (F, N) integer bin matrix, N % HIST_CHUNK == 0.
      ghc_t: (3, N) float32 stats (grad*inbag, hess*inbag, inbag);
        padding rows must be zero.
      row_leaf: (N,) int32 row->leaf map.
      leaf_id: traced int32 scalar.
      num_bins_total: static histogram width B.
      row_chunk: static scan chunk of the compacted buffer.

    Returns the compensated (value, residual) pair of
    build_histograms_pair — collapse with `hi + lo`, or exchange shard
    pairs in fixed order first (parallel/mesh.py pair_allreduce /
    pair_reduce_scatter; the lax.switch holds no collectives, so shards
    on different buckets still meet the reduction in lockstep).
    """
    from .partition import compact_gather_indices
    f, n = bins.shape
    if n % HIST_CHUNK != 0:
        raise ValueError(f"N={n} must be a multiple of {HIST_CHUNK}")
    n_chunks = n // HIST_CHUNK
    buckets = bucket_sizes(n_chunks)
    chunk = min(int(row_chunk), HIST_CHUNK)

    if chunk_mode() == "bincount":
        # single-callback fast path: dynamic shapes are free on the
        # host, so the compaction (flatnonzero), the gather and the
        # chunked Kahan accumulation all happen inside ONE callback —
        # no bucketed lax.switch, no O(N) XLA cumsum/scatter/gather
        # per split. Still a pure per-shard function of (rows, stats),
        # so every collective hook contract holds unchanged.
        return _compacted_bincount(bins, ghc_t, row_leaf, leaf_id,
                                   num_bins_total, chunk)

    mask = row_leaf == leaf_id
    cnt = jnp.sum(mask.astype(jnp.int32))
    idx, _ = cover_index(jnp.int32(0), cnt, n_chunks)

    def make_branch(bk):
        size = bk * HIST_CHUNK

        def branch(mask):
            src = compact_gather_indices(mask, size)
            valid = (src < n).astype(jnp.float32)
            src_c = jnp.minimum(src, n - 1)
            bins_sl = jnp.take(bins, src_c, axis=1)
            ghc_sl = jnp.take(ghc_t, src_c, axis=1) * valid[None, :]
            return build_histograms_pair(bins_sl, ghc_sl.T, num_bins_total,
                                         row_chunk=min(size, chunk))

        return branch

    return jax.lax.switch(idx, [make_branch(b) for b in buckets], mask)
