"""Histogram construction: the hot op of GBDT training.

Reference: the per-feature scalar accumulation loops in
src/io/dense_bin.hpp:16-195 (4-way unrolled CPU scatter-add) and
src/treelearner/feature_histogram.hpp:54-79.

TPU-first design: scatter-add does not vectorize on TPU; instead the
histogram is ONE batched one-hot contraction on the MXU:

    hist[f, b, k] = sum_n [bins[f, n] == b] * ghc[n, k]

where ghc packs the per-row statistics columns (gradient, hessian,
in-leaf count mask — and both children at once: the reference's
"histogram subtraction trick" (serial_tree_learner.cpp:376-379) halves
CPU work; on the MXU both children ride in the same matmul for free
because the stat-column dimension sits far below the 128-lane tile, so
left and right child histograms come out of one pass).

Rows are processed in chunks via `lax.scan` so the one-hot operand
stays small; XLA fuses the compare into the dot operand tiles.
"""

import jax
import jax.numpy as jnp

DEFAULT_ROW_CHUNK = 8192


def build_histograms(bins, ghc, num_bins_total, row_chunk=DEFAULT_ROW_CHUNK):
    """Compute per-feature histograms of the packed row statistics.

    Args:
      bins: (F, N) integer bin matrix (uint8/uint16), N a multiple of
        row_chunk when N > row_chunk (pad rows must carry ghc == 0).
      ghc: (N, K) float32 packed statistics; masked rows are zero.
      num_bins_total: static int B — histogram width (max bins over features).
      row_chunk: static chunk size for the scan.

    Returns:
      (F, B, K) float32 histogram.
    """
    hi, lo = build_histograms_pair(bins, ghc, num_bins_total, row_chunk)
    return hi + lo


def build_histograms_pair(bins, ghc, num_bins_total, row_chunk=DEFAULT_ROW_CHUNK):
    """Compensated (Kahan) accumulation across row chunks: returns the
    (value, compensation) float32 pair, summing per-chunk f32 partials
    with ~f64-equivalent accuracy. The pair representation lets the
    data-parallel learner reduce shard partials in a FIXED order
    (ops-level analog of the reference's f64 accumulators, bin.h:18-26),
    so serial and data-parallel training see histograms that agree to
    ~1e-14 relative instead of f32-reduction-order ulps."""
    f, n = bins.shape
    k = ghc.shape[1]
    b = num_bins_total

    if n <= row_chunk:
        h = _hist_chunk(bins, ghc, b)
        return h, jnp.zeros_like(h)
    if n % row_chunk != 0:
        raise ValueError(f"N={n} must be padded to a multiple of {row_chunk}")
    nchunks = n // row_chunk

    bins_c = bins.reshape(f, nchunks, row_chunk).transpose(1, 0, 2)
    ghc_c = ghc.reshape(nchunks, row_chunk, k)

    def step(carry, xs):
        acc, comp = carry
        bc, gc = xs
        h = _hist_chunk(bc, gc, b)
        y = h - comp
        t = acc + y
        comp = (t - acc) - y
        return (t, comp), None

    zero = jnp.zeros((f, b, k), dtype=jnp.float32)
    (acc, comp), _ = jax.lax.scan(step, (zero, zero), (bins_c, ghc_c))
    return acc, -comp  # Kahan comp holds the NEGATIVE residual


def _hist_chunk(bins_chunk, ghc_chunk, b):
    """One-hot contraction over a row chunk: (F, C), (C, K) -> (F, B, K)."""
    onehot = (bins_chunk[:, :, None] == jnp.arange(b, dtype=jnp.int32)[None, None, :])
    return jnp.einsum("fcb,ck->fbk", onehot.astype(jnp.float32),
                      ghc_chunk.astype(jnp.float32),
                      preferred_element_type=jnp.float32)
