"""Histogram construction: the hot op of GBDT training.

Reference: the per-feature scalar accumulation loops in
src/io/dense_bin.hpp:16-195 (4-way unrolled CPU scatter-add) and
src/treelearner/feature_histogram.hpp:54-79.

TPU-first design: scatter-add does not vectorize on TPU; instead the
histogram is ONE batched one-hot contraction on the MXU:

    hist[f, b, k] = sum_n [bins[f, n] == b] * ghc[n, k]

where ghc packs the per-row statistics columns (gradient, hessian,
in-leaf count mask — and both children at once: the reference's
"histogram subtraction trick" (serial_tree_learner.cpp:376-379) halves
CPU work; on the MXU both children ride in the same matmul for free
because the stat-column dimension sits far below the 128-lane tile, so
left and right child histograms come out of one pass).

Rows are processed in chunks via `lax.scan` so the one-hot operand
stays small; XLA fuses the compare into the dot operand tiles.
"""

import jax
import jax.numpy as jnp

DEFAULT_ROW_CHUNK = 8192


def build_histograms(bins, ghc, num_bins_total, row_chunk=DEFAULT_ROW_CHUNK):
    """Compute per-feature histograms of the packed row statistics.

    Args:
      bins: (F, N) integer bin matrix (uint8/uint16), N a multiple of
        row_chunk when N > row_chunk (pad rows must carry ghc == 0).
      ghc: (N, K) float32 packed statistics; masked rows are zero.
      num_bins_total: static int B — histogram width (max bins over features).
      row_chunk: static chunk size for the scan.

    Returns:
      (F, B, K) float32 histogram.
    """
    f, n = bins.shape
    k = ghc.shape[1]
    b = num_bins_total

    if n <= row_chunk:
        return _hist_chunk(bins, ghc, b)
    if n % row_chunk != 0:
        raise ValueError(f"N={n} must be padded to a multiple of {row_chunk}")
    nchunks = n // row_chunk

    bins_c = bins.reshape(f, nchunks, row_chunk).transpose(1, 0, 2)
    ghc_c = ghc.reshape(nchunks, row_chunk, k)

    def step(acc, xs):
        bc, gc = xs
        return acc + _hist_chunk(bc, gc, b), None

    acc0 = jnp.zeros((f, b, k), dtype=jnp.float32)
    hist, _ = jax.lax.scan(step, acc0, (bins_c, ghc_c))
    return hist


def _hist_chunk(bins_chunk, ghc_chunk, b):
    """One-hot contraction over a row chunk: (F, C), (C, K) -> (F, B, K)."""
    onehot = (bins_chunk[:, :, None] == jnp.arange(b, dtype=jnp.int32)[None, None, :])
    return jnp.einsum("fcb,ck->fbk", onehot.astype(jnp.float32),
                      ghc_chunk.astype(jnp.float32),
                      preferred_element_type=jnp.float32)
