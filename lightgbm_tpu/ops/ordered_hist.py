"""Segment histograms over a leaf-contiguous row layout — the hot op of
the partitioned tree builder (models/partitioned.py).

Reference semantics: ordered_sparse_bin.hpp:25-133 / data_partition.hpp
keep per-leaf row indices contiguous so per-leaf histogram cost is
proportional to leaf size. The TPU translation: rows are kept
PHYSICALLY sorted by leaf (ops/partition.py), a leaf is a position
range [begin, begin+cnt), and its histogram streams only the chunks
covering that range — sequential HBM reads, no gathers, cost
O(leaf_rows) instead of the masked builder's O(N) per split
(ops/pallas_hist.py BASELINE.md bound).

Static shapes under jit come from BUCKETING: segment lengths are
rounded up to a geometric-bucket number of HIST_CHUNK-row chunks
(power-of-two by default, see BUCKET_GROWTH) and
`lax.switch` dispatches to the matching pre-compiled variant; boundary
chunks mask rows outside the range by position (two iota compares —
there is no row_leaf array at all on this path).

Bins are packed 4 features per int32 word (W = ceil(F/4), feature f in
byte f%4 of word f//4): one permutation gather moves 4 features at
once, and the kernel unpacks with a shift+mask (2 VPU ops per feature
per chunk, far below the B x C one-hot compares).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_hist import HIST_CHUNK


def pack_feature_words(bins_u8):
    """(F, N) uint8 bins -> (ceil(F/4), N) int32 packed words (host)."""
    f, n = bins_u8.shape
    w = (f + 3) // 4
    padded = np.zeros((w * 4, n), dtype=np.uint8)
    padded[:f] = bins_u8
    p = padded.reshape(w, 4, n).astype(np.uint32)
    words = p[:, 0] | (p[:, 1] << 8) | (p[:, 2] << 16) | (p[:, 3] << 24)
    return words.view(np.int32)


def unpack_feature(words, feat):
    """Bin column of (traced) feature id `feat` from packed words."""
    word = jnp.take(words, feat >> 2, axis=0)
    return (word >> ((feat & 3) * 8)) & 0xFF


def _parse_bucket_growth():
    import os
    raw = os.environ.get("LIGHTGBM_TPU_BUCKET_GROWTH", "2")
    try:
        growth = int(raw)
    except ValueError:
        raise ValueError(
            f"LIGHTGBM_TPU_BUCKET_GROWTH must be an integer >= 2, got {raw!r}")
    if growth < 2:
        raise ValueError(
            f"LIGHTGBM_TPU_BUCKET_GROWTH must be >= 2, got {raw!r}")
    return growth


# Geometric growth factor of the segment buckets, read ONCE at import
# (consistent for the process lifetime — jitted programs bake it in).
# 2 (default) minimizes streaming waste (<2x per segment) at
# ~log2(n_chunks) compiled kernel variants; LIGHTGBM_TPU_BUCKET_GROWTH=4
# halves the variant count (faster compile) at <4x worst-case waste — a
# knob for tuning compile-time vs throughput on real hardware.
BUCKET_GROWTH = _parse_bucket_growth()


def bucket_sizes(n_chunks):
    """Geometric chunk buckets up to the full array (see BUCKET_GROWTH)."""
    growth = BUCKET_GROWTH
    sizes = []
    b = 1
    while b < n_chunks:
        sizes.append(b)
        b *= growth
    sizes.append(n_chunks)
    return sizes


def canonical_row_chunks(n_chunks):
    """Round a HIST_CHUNK-chunk count up to a 3-bit-mantissa grid
    (m * 2^e, m in [8, 15]) — the shape-bucketing half of the persistent
    compile cache (config.py setup_compilation_cache): datasets whose
    padded row counts land in the same bucket share every lowered
    executable across processes, at <= 1/8 extra padded rows. Counts
    <= 8 are already canonical (too few distinct values to fragment the
    cache)."""
    if n_chunks <= 8:
        return n_chunks
    step = 1 << (n_chunks.bit_length() - 4)
    return -(-n_chunks // step) * step


def cover_index(begin, cnt, n_chunks):
    """Chunk-cover dispatch shared by segment_histograms and the
    partition step (models/partitioned.py _partition_segment): the
    `lax.switch` bucket index + first covered chunk for the position
    range [begin, begin+cnt). Both consumers MUST window with
    `window_start` so their slices agree."""
    c_first = begin // HIST_CHUNK
    c_last = (begin + jnp.maximum(cnt, 1) - 1) // HIST_CHUNK
    needed = c_last - c_first + 1
    idx = jnp.searchsorted(
        jnp.asarray(bucket_sizes(n_chunks), dtype=jnp.int32), needed)
    return idx, c_first


def window_start(c_first, bk, n_chunks):
    """First ROW of the bk-chunk window at c_first, clipped in-bounds
    (a pulled-back window still covers the range; see cover_index)."""
    return jnp.clip(c_first, 0, n_chunks - bk) * HIST_CHUNK


def _seg_hist_kernel(lohi_ref, words_ref, ghc_ref, out_ref, *, f, b_pad):
    """One grid step = one HIST_CHUNK block of the sliced segment."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    c = words_ref.shape[1]
    # 2-D iota, kept 2-D: a bare 1-D iota fails TPU pallas lowering
    # (pallas_guide.md "TPU requires at least 2D iota"), and staying
    # (C, 1) lets the mask broadcast into (C, 3) with no rank changes
    pos = step * c + jax.lax.broadcasted_iota(jnp.int32, (c, 1), 0)
    mask = ((pos >= lohi_ref[0]) & (pos < lohi_ref[1])).astype(jnp.float32)
    ghc_m = ghc_ref[...] * mask                                   # (C, 3)
    b_iota = jax.lax.broadcasted_iota(jnp.int32, (b_pad, c), 0)
    for i in range(f):
        word = words_ref[i >> 2, :]
        bins_f = (word >> ((i & 3) * 8)) & 0xFF
        onehot = (bins_f[None, :] == b_iota).astype(jnp.float32)  # (B_pad, C)
        out_ref[i, :, :] += jax.lax.dot_general(
            onehot, ghc_m, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                   # (B_pad, 3)


def _seg_hist_tpu(words_sl, ghc_sl, lo, hi, f, num_bins_total, n_blocks,
                  interpret=False):
    """Pallas segment histogram over a chunk-aligned slice. `interpret`
    runs the kernel body in pallas interpret mode (CPU) — used by tests
    to validate kernel semantics without TPU hardware."""
    w = words_sl.shape[0]
    b_pad = max(((num_bins_total + 127) // 128) * 128, 128)
    kernel = functools.partial(_seg_hist_kernel, f=f, b_pad=b_pad)
    out = pl.pallas_call(
        kernel,
        interpret=interpret,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # (2,) lo/hi
            pl.BlockSpec((w, HIST_CHUNK), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((HIST_CHUNK, 3), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((f, b_pad, 3), lambda i: (0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((f, b_pad, 3), jnp.float32),
    )(jnp.stack([lo, hi]).astype(jnp.int32), words_sl, ghc_sl)
    return out[:, :num_bins_total, :]


def _seg_hist_xla(words_sl, ghc_sl, lo, hi, f, num_bins_total):
    """XLA fallback (CPU tests / non-TPU): unpack + positional mask +
    the chunked one-hot einsum of ops/histogram.py."""
    from .histogram import build_histograms
    w, n = words_sl.shape
    shifts = jnp.arange(4, dtype=jnp.int32) * 8
    bins = ((words_sl[:, None, :] >> shifts[None, :, None]) & 0xFF)
    bins = bins.reshape(w * 4, n)[:f]
    pos = jnp.arange(n, dtype=jnp.int32)
    mask = ((pos >= lo) & (pos < hi)).astype(jnp.float32)
    ghc_m = ghc_sl * mask[:, None]
    return build_histograms(bins, ghc_m, num_bins_total,
                            row_chunk=min(n, HIST_CHUNK))


def segment_histograms(words, ghc_t, begin, cnt, num_bins_total, f,
                       interpret_backend=None, interpret=False):
    """hist[f, b, k] over the position range [begin, begin+cnt).

    Args:
      words: (W, N) int32 packed bins (leaf-ordered), N % HIST_CHUNK == 0.
      ghc_t: (3, N) float32 leaf-ordered stats (grad*inbag, hess*inbag,
        inbag); padding rows must be zero.
      begin, cnt: traced int32 segment bounds.
      num_bins_total: static histogram width B.
      f: static real feature count (<= 4W).

    Returns (F, B, 3) float32. Cost scales with the geometric chunk
    bucket covering the segment (bucket_sizes), not with N.
    """
    w, n = words.shape
    if n % HIST_CHUNK != 0:
        raise ValueError(f"N={n} must be a multiple of {HIST_CHUNK}")
    n_chunks = n // HIST_CHUNK
    buckets = bucket_sizes(n_chunks)

    begin = begin.astype(jnp.int32)
    cnt = jnp.maximum(cnt, 0).astype(jnp.int32)
    idx, c_first = cover_index(begin, cnt, n_chunks)

    if interpret_backend is None:
        # same dispatch as ops/pallas_hist.py masked_histograms: TPU
        # with hist_mode auto/pallas runs the kernel; einsum/segment/
        # bincount (or LIGHTGBM_TPU_DISABLE_PALLAS=1) force the XLA
        # path (bench.py fallback ladder); an explicit
        # interpret_backend wins
        from .histogram import use_pallas
        on_tpu = use_pallas()
    else:
        on_tpu = interpret_backend == "tpu"

    def make_branch(bk):
        def branch(begin, cnt):
            start = window_start(c_first, bk, n_chunks)
            words_sl = jax.lax.dynamic_slice(
                words, (jnp.int32(0), start), (w, bk * HIST_CHUNK))
            ghc_sl = jax.lax.dynamic_slice(
                ghc_t, (jnp.int32(0), start), (3, bk * HIST_CHUNK)).T
            lo = begin - start
            hi = lo + cnt
            if on_tpu:
                return _seg_hist_tpu(words_sl, ghc_sl, lo, hi, f,
                                     num_bins_total, bk, interpret=interpret)
            return _seg_hist_xla(words_sl, ghc_sl, lo, hi, f, num_bins_total)
        return branch

    return jax.lax.switch(idx, [make_branch(b) for b in buckets], begin, cnt)
