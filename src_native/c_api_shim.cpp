/*
 * lib_lightgbm.so — the C API surface.
 *
 * Reference: src/c_api.cpp + include/LightGBM/c_api.h:29-527 (38
 * LGBM_* exports, DatasetHandle/BoosterHandle opaque pointers,
 * thread-local last-error with the API_BEGIN/API_END trap,
 * c_api.h:547-573).
 *
 * Where the reference implements the API over its C++ core, the TPU
 * build's core is the JAX graph: this shim embeds CPython and forwards
 * every call to lightgbm_tpu.capi_bridge, which does all pointer
 * marshalling with ctypes/numpy. Handles are strong PyObject
 * references released by the matching *Free call. Every entry point
 * takes the GIL, so the library is callable from any thread, from a
 * host Python process (ctypes) or from a plain C program.
 */

#include <Python.h>

#include <cstdint>
#include <cstdio>
#include <string>

#define DllExport extern "C" __attribute__((visibility("default")))

typedef void* DatasetHandle;
typedef void* BoosterHandle;

static thread_local std::string g_last_error = "Everything is fine";

DllExport const char* LGBM_GetLastError() { return g_last_error.c_str(); }

/* c_api.h:554-556 keeps this inline for in-process use; exporting it
 * lets FFI hosts stamp their own error text into the same thread-local
 * slot GetLastError reads. */
DllExport void LGBM_SetLastError(const char* msg) {
  g_last_error = msg ? msg : "";
}

namespace {

PyObject* g_bridge = nullptr;

/* Initialize the interpreter (when hosted by a non-Python process) and
 * import the bridge module once. Returns borrowed bridge ref or null. */
PyObject* bridge() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
#if PY_VERSION_HEX < 0x03090000
    PyEval_InitThreads();
#endif
  }
  if (g_bridge == nullptr) {
    PyGILState_STATE st = PyGILState_Ensure();
    g_bridge = PyImport_ImportModule("lightgbm_tpu.capi_bridge");
    if (g_bridge == nullptr) {
      PyErr_Print();
    }
    PyGILState_Release(st);
  }
  return g_bridge;
}

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* msg = PyUnicode_AsUTF8(s);
      g_last_error = msg != nullptr ? msg : "unknown error";
      Py_DECREF(s);
    }
  } else {
    g_last_error = "unknown error";
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

/* Call bridge.<name>(*args). Returns new ref or null (error stored). */
PyObject* call(const char* name, const char* fmt, ...) {
  PyObject* mod = bridge();
  if (mod == nullptr) {
    g_last_error = "lightgbm_tpu.capi_bridge import failed";
    return nullptr;
  }
  PyGILState_STATE st = PyGILState_Ensure();
  va_list vargs;
  va_start(vargs, fmt);
  PyObject* args = Py_VaBuildValue(fmt, vargs);
  va_end(vargs);
  PyObject* result = nullptr;
  if (args != nullptr) {
    PyObject* fn = PyObject_GetAttrString(mod, name);
    if (fn != nullptr) {
      result = PyObject_CallObject(fn, args);
      Py_DECREF(fn);
    }
    Py_DECREF(args);
  }
  if (result == nullptr) {
    set_error_from_python();
  }
  PyGILState_Release(st);
  return result;
}

/* Store a new-ref result as an opaque handle (keeps the strong ref). */
int to_handle(PyObject* result, void** out) {
  if (result == nullptr) return -1;
  *out = static_cast<void*>(result);
  return 0;
}

/* Result ignored beyond success/failure.
 * NOTE: must not touch thread state before PyGILState_Ensure — ctypes
 * callers release the GIL around the C call, so this thread does not
 * hold it on entry. */
int to_status(PyObject* result) {
  if (result == nullptr) return -1;
  PyGILState_STATE st = PyGILState_Ensure();
  Py_DECREF(result);
  PyGILState_Release(st);
  return 0;
}

/* Result is an int scalar written to *out. */
template <typename T>
int to_int(PyObject* result, T* out) {
  if (result == nullptr) return -1;
  PyGILState_STATE st = PyGILState_Ensure();
  *out = static_cast<T>(PyLong_AsLongLong(result));
  Py_DECREF(result);
  PyGILState_Release(st);
  return 0;
}

int free_handle(void* handle) {
  if (handle != nullptr) {
    PyGILState_STATE st = PyGILState_Ensure();
    Py_DECREF(static_cast<PyObject*>(handle));
    PyGILState_Release(st);
  }
  return 0;
}

PyObject* none_or(void* handle) {
  /* borrowed-ref helper for optional handle args ("O" format) */
  return handle != nullptr ? static_cast<PyObject*>(handle) : Py_None;
}

}  // namespace

/* ----------------------------------------------------------- datasets */

DllExport int LGBM_DatasetCreateFromFile(const char* filename,
                                         const char* parameters,
                                         const DatasetHandle reference,
                                         DatasetHandle* out) {
  return to_handle(call("dataset_create_from_file", "(ssO)", filename,
                        parameters, none_or(reference)),
                   out);
}

DllExport int LGBM_DatasetCreateFromCSR(const void* indptr, int indptr_type,
                                        const int32_t* indices,
                                        const void* data, int data_type,
                                        int64_t nindptr, int64_t nelem,
                                        int64_t num_col,
                                        const char* parameters,
                                        const DatasetHandle reference,
                                        DatasetHandle* out) {
  return to_handle(
      call("dataset_create_from_csr", "(KiKKiLLLsO)", (unsigned long long)indptr,
           indptr_type, (unsigned long long)indices, (unsigned long long)data,
           data_type, (long long)nindptr, (long long)nelem, (long long)num_col,
           parameters, none_or(reference)),
      out);
}

DllExport int LGBM_DatasetCreateFromCSC(const void* col_ptr, int col_ptr_type,
                                        const int32_t* indices,
                                        const void* data, int data_type,
                                        int64_t ncol_ptr, int64_t nelem,
                                        int64_t num_row,
                                        const char* parameters,
                                        const DatasetHandle reference,
                                        DatasetHandle* out) {
  return to_handle(
      call("dataset_create_from_csc", "(KiKKiLLLsO)",
           (unsigned long long)col_ptr, col_ptr_type,
           (unsigned long long)indices, (unsigned long long)data, data_type,
           (long long)ncol_ptr, (long long)nelem, (long long)num_row,
           parameters, none_or(reference)),
      out);
}

DllExport int LGBM_DatasetCreateFromMat(const void* data, int data_type,
                                        int32_t nrow, int32_t ncol,
                                        int is_row_major,
                                        const char* parameters,
                                        const DatasetHandle reference,
                                        DatasetHandle* out) {
  return to_handle(call("dataset_create_from_mat", "(KiiiisO)",
                        (unsigned long long)data, data_type, (int)nrow,
                        (int)ncol, is_row_major, parameters,
                        none_or(reference)),
                   out);
}

DllExport int LGBM_DatasetGetSubset(const DatasetHandle handle,
                                    const int32_t* used_row_indices,
                                    int32_t num_used_row_indices,
                                    const char* parameters,
                                    DatasetHandle* out) {
  return to_handle(call("dataset_get_subset", "(OKis)", none_or(handle),
                        (unsigned long long)used_row_indices,
                        (int)num_used_row_indices, parameters),
                   out);
}

DllExport int LGBM_DatasetSetFeatureNames(DatasetHandle handle,
                                          const char** feature_names,
                                          int64_t num_feature_names) {
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject* names = PyList_New(num_feature_names);
  for (int64_t i = 0; i < num_feature_names; ++i) {
    PyList_SetItem(names, i, PyUnicode_FromString(feature_names[i]));
  }
  PyGILState_Release(st);
  int ret = to_status(call("dataset_set_feature_names", "(ON)",
                           none_or(handle), names));
  return ret;
}

DllExport int LGBM_DatasetFree(DatasetHandle handle) {
  return free_handle(handle);
}

DllExport int LGBM_DatasetSaveBinary(DatasetHandle handle,
                                     const char* filename) {
  return to_status(call("dataset_save_binary", "(Os)", none_or(handle),
                        filename));
}

DllExport int LGBM_DatasetSetField(DatasetHandle handle,
                                   const char* field_name,
                                   const void* field_data,
                                   int64_t num_element, int type) {
  return to_status(call("dataset_set_field", "(OsKLi)", none_or(handle),
                        field_name, (unsigned long long)field_data,
                        (long long)num_element, type));
}

DllExport int LGBM_DatasetGetField(DatasetHandle handle,
                                   const char* field_name, int64_t* out_len,
                                   const void** out_ptr, int* out_type) {
  return to_status(call("dataset_get_field", "(OsKKK)", none_or(handle),
                        field_name, (unsigned long long)out_len,
                        (unsigned long long)out_ptr,
                        (unsigned long long)out_type));
}

DllExport int LGBM_DatasetGetNumData(DatasetHandle handle, int64_t* out) {
  return to_int(call("dataset_get_num_data", "(O)", none_or(handle)), out);
}

DllExport int LGBM_DatasetGetNumFeature(DatasetHandle handle, int64_t* out) {
  return to_int(call("dataset_get_num_feature", "(O)", none_or(handle)), out);
}

/* ----------------------------------------------------------- boosters */

DllExport int LGBM_BoosterCreate(const DatasetHandle train_data,
                                 const char* parameters, BoosterHandle* out) {
  return to_handle(call("booster_create", "(Os)", none_or(train_data),
                        parameters),
                   out);
}

DllExport int LGBM_BoosterCreateFromModelfile(const char* filename,
                                              int64_t* out_num_iterations,
                                              BoosterHandle* out) {
  return to_handle(call("booster_create_from_modelfile", "(sK)", filename,
                        (unsigned long long)out_num_iterations),
                   out);
}

DllExport int LGBM_BoosterFree(BoosterHandle handle) {
  return free_handle(handle);
}

DllExport int LGBM_BoosterMerge(BoosterHandle handle,
                                BoosterHandle other_handle) {
  return to_status(call("booster_merge", "(OO)", none_or(handle),
                        none_or(other_handle)));
}

DllExport int LGBM_BoosterAddValidData(BoosterHandle handle,
                                       const DatasetHandle valid_data) {
  return to_status(call("booster_add_valid_data", "(OO)", none_or(handle),
                        none_or(valid_data)));
}

DllExport int LGBM_BoosterResetTrainingData(BoosterHandle handle,
                                            const DatasetHandle train_data) {
  return to_status(call("booster_reset_training_data", "(OO)",
                        none_or(handle), none_or(train_data)));
}

DllExport int LGBM_BoosterResetParameter(BoosterHandle handle,
                                         const char* parameters) {
  return to_status(call("booster_reset_parameter", "(Os)", none_or(handle),
                        parameters));
}

DllExport int LGBM_BoosterGetNumClasses(BoosterHandle handle,
                                        int64_t* out_len) {
  return to_int(call("booster_get_num_classes", "(O)", none_or(handle)),
                out_len);
}

DllExport int LGBM_BoosterUpdateOneIter(BoosterHandle handle,
                                        int* is_finished) {
  return to_status(call("booster_update_one_iter", "(OK)", none_or(handle),
                        (unsigned long long)is_finished));
}

DllExport int LGBM_BoosterUpdateOneIterCustom(BoosterHandle handle,
                                              const float* grad,
                                              const float* hess,
                                              int* is_finished) {
  return to_status(call("booster_update_one_iter_custom", "(OKKK)",
                        none_or(handle), (unsigned long long)grad,
                        (unsigned long long)hess,
                        (unsigned long long)is_finished));
}

DllExport int LGBM_BoosterRollbackOneIter(BoosterHandle handle) {
  return to_status(call("booster_rollback_one_iter", "(O)", none_or(handle)));
}

DllExport int LGBM_BoosterGetCurrentIteration(BoosterHandle handle,
                                              int64_t* out_iteration) {
  return to_int(call("booster_get_current_iteration", "(O)", none_or(handle)),
                out_iteration);
}

DllExport int LGBM_BoosterGetEvalCounts(BoosterHandle handle,
                                        int64_t* out_len) {
  return to_int(call("booster_get_eval_counts", "(O)", none_or(handle)),
                out_len);
}

DllExport int LGBM_BoosterGetEvalNames(BoosterHandle handle, int64_t* out_len,
                                       char** out_strs) {
  return to_int(call("booster_get_eval_names", "(OK)", none_or(handle),
                     (unsigned long long)out_strs),
                out_len);
}

DllExport int LGBM_BoosterGetEval(BoosterHandle handle, int data_idx,
                                  int64_t* out_len, float* out_results) {
  return to_int(call("booster_get_eval", "(OiK)", none_or(handle), data_idx,
                     (unsigned long long)out_results),
                out_len);
}

DllExport int LGBM_BoosterGetPredict(BoosterHandle handle, int data_idx,
                                     int64_t* out_len, float* out_result) {
  return to_int(call("booster_get_predict", "(OiK)", none_or(handle),
                     data_idx, (unsigned long long)out_result),
                out_len);
}

DllExport int LGBM_BoosterPredictForFile(BoosterHandle handle,
                                         const char* data_filename,
                                         int data_has_header,
                                         int predict_type,
                                         int64_t num_iteration,
                                         const char* result_filename) {
  return to_status(call("booster_predict_for_file", "(OsiiLs)",
                        none_or(handle), data_filename, data_has_header,
                        predict_type, (long long)num_iteration,
                        result_filename));
}

DllExport int LGBM_BoosterPredictForCSR(
    BoosterHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type, int64_t nindptr,
    int64_t nelem, int64_t num_col, int predict_type, int64_t num_iteration,
    int64_t* out_len, double* out_result) {
  return to_status(call(
      "booster_predict_for_csr", "(OKiKKiLLLiLKK)", none_or(handle),
      (unsigned long long)indptr, indptr_type, (unsigned long long)indices,
      (unsigned long long)data, data_type, (long long)nindptr,
      (long long)nelem, (long long)num_col, predict_type,
      (long long)num_iteration, (unsigned long long)out_len,
      (unsigned long long)out_result));
}

DllExport int LGBM_BoosterPredictForMat(BoosterHandle handle,
                                        const void* data, int data_type,
                                        int32_t nrow, int32_t ncol,
                                        int is_row_major, int predict_type,
                                        int64_t num_iteration,
                                        int64_t* out_len, double* out_result) {
  return to_status(call("booster_predict_for_mat", "(OKiiiiiLKK)",
                        none_or(handle), (unsigned long long)data, data_type,
                        (int)nrow, (int)ncol, is_row_major, predict_type,
                        (long long)num_iteration,
                        (unsigned long long)out_len,
                        (unsigned long long)out_result));
}

DllExport int LGBM_BoosterSaveModel(BoosterHandle handle, int num_iteration,
                                    const char* filename) {
  return to_status(call("booster_save_model", "(Ois)", none_or(handle),
                        num_iteration, filename));
}

DllExport int LGBM_BoosterDumpModel(BoosterHandle handle, int buffer_len,
                                    int64_t* out_len, char** out_str) {
  return to_status(call("booster_dump_model", "(OiKK)", none_or(handle),
                        buffer_len, (unsigned long long)out_len,
                        (unsigned long long)(out_str ? *out_str : nullptr)));
}

DllExport int LGBM_BoosterGetLeafValue(BoosterHandle handle, int tree_idx,
                                       int leaf_idx, float* out_val) {
  return to_status(call("booster_get_leaf_value", "(OiiK)", none_or(handle),
                        tree_idx, leaf_idx, (unsigned long long)out_val));
}

DllExport int LGBM_BoosterSetLeafValue(BoosterHandle handle, int tree_idx,
                                       int leaf_idx, float val) {
  return to_status(call("booster_set_leaf_value", "(Oiif)", none_or(handle),
                        tree_idx, leaf_idx, (double)val));
}
