"""Package installer (parity with the reference's python-package/setup.py).

The library is pure Python on top of the baked-in jax stack; the C API
shim (`make` -> lib_lightgbm.so) is built separately and only needed by
ctypes consumers of the reference C surface.
"""

from setuptools import find_packages, setup

setup(
    name="lightgbm_tpu",
    version="0.1.0",
    description=("TPU-native gradient boosting framework with the "
                 "capability surface of early LightGBM"),
    packages=find_packages(include=["lightgbm_tpu", "lightgbm_tpu.*"]),
    python_requires=">=3.10",
    install_requires=["numpy", "pandas", "jax"],
)
